"""RTMP — live media streaming protocol (client + server).

Reference: src/brpc/rtmp.{h,cpp} (RtmpClient/RtmpClientStream/
RtmpServerStream/RtmpService API at rtmp.h:723-1130),
src/brpc/policy/rtmp_protocol.cpp (3677 L: handshake, chunk codec,
protocol-control and command dispatch), src/brpc/amf.{h,cpp} (AMF0, see
policy/amf.py).  The capability surface is the reference's: a server
hosts an RtmpService whose new_stream() returns per-stream handlers with
on_publish/on_play/on_meta_data/on_audio/on_video callbacks; a client
connects, creates streams, and publishes or plays.  Mechanism is this
framework's: the chunk/command machinery rides the existing Socket /
InputMessenger runtime (protocol-detected alongside every other wire
protocol on the same port), per-stream delivery is serialized through an
ExecutionQueue exactly like Streaming RPC, and waits use tasklet-aware
countdown events.

Wire format per Adobe's public RTMP specification: simple (non-digest)
AND digest ("complex") handshake C0C1C2/S0S1S2 — the server auto-detects
a digest-mode C1 (HMAC-SHA256 with the Genuine-FP key, schemes 0 and 1)
and answers with a digest-mode S1/S2 (rtmp_protocol.cpp's
"simple_handshake/complex handshake" split; FMS rejects H.264 publishes
from non-digest peers, which is why the complex form exists at all);
clients opt in via the ``rtmp_client_digest`` flag — chunk basic+message
headers fmt 0-3 with extended timestamps, protocol control messages 1-6,
AMF0 command/data messages, aggregate message splitting.
"""
from __future__ import annotations

import os
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..butil.endpoint import EndPoint, parse_endpoint
from ..butil.iobuf import IOBuf
from ..butil import logging as log
from ..bthread.countdown import CountdownEvent
from ..bthread.execution_queue import ExecutionQueue
from ..rpc import errors
from ..butil import flags as _flags
from ..rpc.protocol import (CONNECTION_TYPE_SINGLE, ParseResult, Protocol,
                            register_protocol)
from . import amf

_flags.define_flag("rtmp_client_digest", False,
                   "RTMP clients perform the digest (complex) handshake "
                   "instead of the simple one (required by FMS for "
                   "H.264 publishes)")

# ---- message type ids (rtmp_protocol.cpp message dispatch) -------------

MSG_SET_CHUNK_SIZE = 1
MSG_ABORT = 2
MSG_ACK = 3
MSG_USER_CONTROL = 4
MSG_WINDOW_ACK_SIZE = 5
MSG_SET_PEER_BANDWIDTH = 6
MSG_AUDIO = 8
MSG_VIDEO = 9
MSG_DATA_AMF3 = 15
MSG_SHARED_OBJECT_AMF3 = 16
MSG_COMMAND_AMF3 = 17
MSG_DATA_AMF0 = 18
MSG_SHARED_OBJECT_AMF0 = 19
MSG_COMMAND_AMF0 = 20
MSG_AGGREGATE = 22

# user-control event types
UC_STREAM_BEGIN = 0
UC_STREAM_EOF = 1
UC_STREAM_DRY = 2
UC_SET_BUFFER_LENGTH = 3
UC_STREAM_IS_RECORDED = 4
UC_PING_REQUEST = 6
UC_PING_RESPONSE = 7

# chunk-stream ids we originate on (any id >= 3 is an ordinary channel)
CSID_CONTROL = 2            # protocol control (spec-mandated)
CSID_COMMAND = 3            # NetConnection commands
CSID_STATUS = 5             # onStatus / stream-level commands
CSID_AUDIO = 6
CSID_VIDEO = 7
CSID_DATA = 8

HANDSHAKE_SIZE = 1536
RTMP_VERSION = 3
DEFAULT_CHUNK_SIZE = 128
OUT_CHUNK_SIZE = 4096
DEFAULT_WINDOW_ACK_SIZE = 2500000
_MAX_MESSAGE_SIZE = 64 << 20

_TIMESTAMP_MASK = 0xFFFFFF

# ---- digest ("complex") handshake -------------------------------------
# rtmp_protocol.cpp (RtmpUnsentHandshakeC/S + ComputeDigestBase): C1/S1
# embed an HMAC-SHA256 digest at an offset derived from 4 offset bytes;
# scheme 0 puts the offset field right after time+version (bytes 8-12),
# scheme 1 after the 764-byte key block (bytes 772-776).  The published
# Genuine-Adobe constants (the same tables the reference carries):

_FP_KEY = (b"Genuine Adobe Flash Player 001"
           b"\xF0\xEE\xC2\x4A\x80\x68\xBE\xE8\x2E\x00\xD0\xD1\x02\x9E"
           b"\x7E\x57\x6E\xEC\x5D\x2D\x29\x80\x6F\xAB\x93\xB8\xE6\x36"
           b"\xCF\xEB\x31\xAE")                       # 62 bytes
_FMS_KEY = (b"Genuine Adobe Flash Media Server 001"
            b"\xF0\xEE\xC2\x4A\x80\x68\xBE\xE8\x2E\x00\xD0\xD1\x02\x9E"
            b"\x7E\x57\x6E\xEC\x5D\x2D\x29\x80\x6F\xAB\x93\xB8\xE6\x36"
            b"\xCF\xEB\x31\xAE")                      # 68 bytes
_DIGEST_SIZE = 32
# digest-mode C1/S1 advertise a nonzero "version" field (flash/FMS
# version); zero means the peer speaks the simple handshake only
_C1_VERSION = b"\x80\x00\x07\x02"
_S1_VERSION = b"\x04\x05\x00\x01"


def _hmac_sha256(key: bytes, msg: bytes) -> bytes:
    import hashlib
    import hmac as _hmac
    return _hmac.new(key, msg, hashlib.sha256).digest()


def _digest_offset(block: bytes, scheme: int) -> int:
    """Digest offset within the 1536-byte block for the given scheme."""
    if scheme == 0:
        base, field = 12, block[8:12]
    else:
        base, field = 776, block[772:776]
    return base + sum(field) % 728


def _embedded_digest(block: bytes, scheme: int):
    """(digest, joined-rest) at the scheme's offset; the digest is
    valid iff HMAC(key, rest) reproduces it."""
    off = _digest_offset(block, scheme)
    digest = block[off:off + _DIGEST_SIZE]
    rest = block[:off] + block[off + _DIGEST_SIZE:]
    return digest, rest


def find_handshake_digest(block: bytes, key: bytes = _FP_KEY[:30]):
    """Locate + validate a digest-mode C1/S1.  Returns the 32-byte
    digest, or None when neither scheme validates (a simple-handshake
    peer)."""
    for scheme in (0, 1):
        digest, rest = _embedded_digest(block, scheme)
        if _hmac_sha256(key, rest) == digest:
            return digest
    return None


def make_digest_block(version: bytes, key: bytes,
                      rand: Optional[bytes] = None) -> bytes:
    """Build a digest-mode C1/S1 (scheme 0): time + version + 1528
    random bytes with the HMAC digest embedded at the derived offset.
    ``rand`` pins the randomness for fixture recording."""
    if rand is None:
        rand = os.urandom(HANDSHAKE_SIZE - 8)
    assert len(rand) == HANDSHAKE_SIZE - 8
    block = bytearray(struct.pack(">I", int(time.monotonic()) & 0xFFFFFFFF)
                      + version + rand)
    off = _digest_offset(bytes(block), 0)
    digest = _hmac_sha256(key, bytes(block[:off])
                          + bytes(block[off + _DIGEST_SIZE:]))
    block[off:off + _DIGEST_SIZE] = digest
    return bytes(block)


def make_handshake_response2(peer_digest: bytes, full_key: bytes,
                             rand: Optional[bytes] = None) -> bytes:
    """Digest-mode C2/S2: 1504 random bytes + HMAC over them, keyed with
    HMAC(full_key, peer's C1/S1 digest) — each side proves it read the
    other's digest.  S2 uses the full FMS key, C2 the full FP key."""
    if rand is None:
        rand = os.urandom(HANDSHAKE_SIZE - _DIGEST_SIZE)
    assert len(rand) == HANDSHAKE_SIZE - _DIGEST_SIZE
    key = _hmac_sha256(full_key, peer_digest)
    return rand + _hmac_sha256(key, rand)


def validate_handshake_response2(block: bytes, own_digest: bytes,
                                 full_key: bytes) -> bool:
    rand, mac = block[:-_DIGEST_SIZE], block[-_DIGEST_SIZE:]
    key = _hmac_sha256(full_key, own_digest)
    return _hmac_sha256(key, rand) == mac


class RtmpMessage:
    __slots__ = ("type", "timestamp", "msid", "body")

    def __init__(self, type: int, timestamp: int, msid: int, body: bytes):
        self.type = type
        self.timestamp = timestamp
        self.msid = msid
        self.body = body


class _InChunkState:
    """Receive-side per-csid chunk state (the reference keeps this in
    RtmpChunkStream, rtmp_protocol.cpp)."""
    __slots__ = ("timestamp", "ts_delta", "msg_len", "msg_type", "msid",
                 "has_ext_ts", "partial", "msg_remaining")

    def __init__(self):
        self.timestamp = 0
        self.ts_delta = 0
        self.msg_len = 0
        self.msg_type = 0
        self.msid = 0
        self.has_ext_ts = False
        self.partial = bytearray()
        self.msg_remaining = 0


from ..butil.misc import p24 as _p24, u24 as _u24  # noqa: E402


# ---- stream objects ----------------------------------------------------

class _RtmpStreamBase:
    """Shared stream machinery: an ExecutionQueue serializes all upcalls
    for the stream (the reference serializes through the socket's
    dispatch; we reuse the Streaming-RPC delivery pattern)."""

    def __init__(self):
        self._conn: Optional["RtmpConnection"] = None
        self.stream_id = 0                    # RTMP message stream id
        self._eq: Optional[ExecutionQueue] = None
        self._closed = False

    # -- user-overridable callbacks (rtmp.h RtmpStreamBase:723-) --------
    def on_meta_data(self, meta: Dict[str, Any], name: str = "onMetaData"
                     ) -> None:
        pass

    def on_audio_message(self, timestamp: int, data: bytes) -> None:
        pass

    def on_video_message(self, timestamp: int, data: bytes) -> None:
        pass

    def on_user_control(self, event: int, data: bytes) -> None:
        pass

    def on_stop(self) -> None:
        pass

    # -- sending --------------------------------------------------------
    def send_audio_message(self, data: bytes, timestamp: int = 0) -> int:
        return self._send_av(MSG_AUDIO, CSID_AUDIO, data, timestamp)

    def send_video_message(self, data: bytes, timestamp: int = 0) -> int:
        return self._send_av(MSG_VIDEO, CSID_VIDEO, data, timestamp)

    def send_meta_data(self, meta: Dict[str, Any],
                       name: str = "onMetaData", timestamp: int = 0) -> int:
        body = amf.encode(name, amf.EcmaArray(meta))
        return self._send_av(MSG_DATA_AMF0, CSID_DATA, body, timestamp)

    def _send_av(self, mtype: int, csid: int, data: bytes,
                 timestamp: int) -> int:
        conn = self._conn
        if conn is None or self._closed:
            return errors.EINVAL
        return conn.send_message(csid, self.stream_id, mtype, timestamp,
                                 bytes(data))

    # -- delivery (reader side) ----------------------------------------
    def _ensure_eq(self) -> ExecutionQueue:
        if self._eq is None:
            self._eq = ExecutionQueue(self._consume)
        return self._eq

    def _deliver(self, fn: Callable, *args) -> None:
        self._ensure_eq().execute((fn, args))

    def _consume(self, it) -> None:
        for fn, args in it:
            try:
                fn(*args)
            except Exception as e:
                log.error("rtmp stream callback raised: %s", e,
                          exc_info=True)

    def _shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._deliver(self.on_stop)
        if self._eq is not None:
            self._eq.stop()


class RtmpServerStream(_RtmpStreamBase):
    """Server side of one RTMP stream (rtmp.h:975-1130).  Subclass and
    override on_publish/on_play plus the base callbacks."""

    def __init__(self):
        super().__init__()
        self.publish_name = ""
        self.play_name = ""
        self.remote_side: Optional[EndPoint] = None

    def on_publish(self, name: str, publish_type: str = "live") -> int:
        """Return 0 to accept the publish, nonzero to reject."""
        return 0

    def on_play(self, name: str) -> int:
        """Return 0 to accept the play, nonzero to reject."""
        return 0

    def send_stop_message(self, description: str = "") -> int:
        """NetStream.Play.Stop to a player (rtmp.h SendStopMessage)."""
        conn = self._conn
        if conn is None:
            return errors.EINVAL
        return conn._send_status(self.stream_id, "status",
                                 "NetStream.Play.Stop",
                                 description or "Stopped.")


class RtmpService:
    """Server-side factory: one RtmpServerStream per created stream
    (rtmp.h RtmpService::NewStream).  Register via Server.add_service."""

    SERVICE_NAME = "rtmp"

    def new_stream(self, remote_side: Optional[EndPoint],
                   connect_info: Dict[str, Any]) -> RtmpServerStream:
        return RtmpServerStream()


class RtmpClientStream(_RtmpStreamBase):
    """Client side of one RTMP stream (rtmp.h:723-880): publish() or
    play() after creation; override base callbacks to receive media."""

    _TERMINAL_CODE_MARKS = ("Failed", "NotFound", "BadName", "Closed",
                            "InvalidArg", "Denied")

    def __init__(self):
        super().__init__()
        self._status_lock = threading.Lock()
        self._status_queue: List[Dict[str, Any]] = []
        self._status_event = CountdownEvent(1)
        self._status_code = ""
        self._status_info: Dict[str, Any] = {}

    # reader side: onStatus routed here
    def _on_status(self, info: Dict[str, Any]) -> None:
        with self._status_lock:
            self._status_queue.append(info)
            self._status_event.signal()
        self._deliver(self.on_status, info)

    def on_status(self, info: Dict[str, Any]) -> None:
        pass

    def _wait_status(self, want: str, timeout: float) -> int:
        """Wait for a terminal status: the wanted code succeeds, an
        error-level or *.Failed/NotFound/... code fails; informational
        codes in between (NetStream.Play.Reset) are consumed and waiting
        continues."""
        deadline = time.monotonic() + timeout
        while True:
            with self._status_lock:
                while self._status_queue:
                    info = self._status_queue.pop(0)
                    code = str(info.get("code", ""))
                    self._status_code = code
                    self._status_info = info
                    if want in code:
                        return 0
                    if info.get("level") == "error" or any(
                            m in code for m in self._TERMINAL_CODE_MARKS):
                        return errors.EREQUEST
                self._status_event.reset(1)
            remaining = deadline - time.monotonic()
            if remaining <= 0 or self._status_event.wait(remaining) != 0:
                return errors.ERPCTIMEDOUT

    def publish(self, name: str, publish_type: str = "live",
                timeout: float = 5.0) -> int:
        conn, err = self._require_conn()
        if err:
            return err
        body = amf.encode("publish", 0.0, None, name, publish_type)
        conn.send_message(CSID_STATUS, self.stream_id, MSG_COMMAND_AMF0, 0,
                          body)
        return self._wait_status("Publish.Start", timeout)

    def play(self, name: str, start: float = -2.0,
             timeout: float = 5.0) -> int:
        conn, err = self._require_conn()
        if err:
            return err
        body = amf.encode("play", 0.0, None, name, start)
        conn.send_message(CSID_STATUS, self.stream_id, MSG_COMMAND_AMF0, 0,
                          body)
        return self._wait_status("Play.Start", timeout)

    def close(self) -> None:
        conn = self._conn
        if conn is not None and not self._closed:
            body = amf.encode("deleteStream", 0.0, None,
                              float(self.stream_id))
            conn.send_message(CSID_COMMAND, 0, MSG_COMMAND_AMF0, 0, body)
            conn._drop_stream(self.stream_id)
        self._shutdown()

    def _require_conn(self):
        if self._conn is None or self._closed:
            return None, errors.EINVAL
        return self._conn, 0


# ---- the connection state machine --------------------------------------

_HS_WAIT_C0C1 = 0           # server: waiting for C0+C1
_HS_WAIT_C2 = 1             # server: waiting for C2
_HS_WAIT_S0S1S2 = 2         # client: waiting for S0+S1+S2
_ESTABLISHED = 3


class RtmpConnection:
    """Per-socket RTMP state: handshake progress, chunk codec state both
    directions, message-stream registry, pending transactions.  Attached
    as socket._rtmp_conn (the pattern h2 uses for its connection state)."""

    def __init__(self, socket, is_server: bool, server=None):
        self.socket = socket
        self.is_server = is_server
        self.server = server
        self.state = _HS_WAIT_C0C1 if is_server else _HS_WAIT_S0S1S2
        self.in_chunk_size = DEFAULT_CHUNK_SIZE
        self.out_chunk_size = DEFAULT_CHUNK_SIZE
        self.ack_window = DEFAULT_WINDOW_ACK_SIZE   # peer-announced
        self.in_bytes_total = 0
        self.in_bytes_unacked = 0
        self.connect_info: Dict[str, Any] = {}
        self.connected = CountdownEvent(1)          # client: connect done
        self.connect_error = 0
        self._in_streams: Dict[int, _InChunkState] = {}
        self._streams: Dict[int, _RtmpStreamBase] = {}
        self._streams_lock = threading.Lock()
        self._next_msid = 1
        self._next_txn = 2                          # 1 was "connect"
        self._pending: Dict[int, tuple] = {}        # txn -> (event, box)
        self._pending_lock = threading.Lock()
        self._out_lock = threading.RLock()
        self._c1_sent = b""
        self._c1_digest: Optional[bytes] = None
        self._connect_request: Dict[str, Any] = {}
        socket.on_failed_callbacks.append(self._on_socket_failed)

    # ---- outbound ------------------------------------------------------

    def _start_client_handshake(self) -> None:
        if _flags.get_flag("rtmp_client_digest"):
            c1 = make_digest_block(_C1_VERSION, _FP_KEY[:30])
            self._c1_digest = find_handshake_digest(c1)
        else:
            c1 = struct.pack(">II", int(time.monotonic()) & 0xFFFFFFFF, 0) \
                + os.urandom(HANDSHAKE_SIZE - 8)
            self._c1_digest = None
        self._c1_sent = c1
        self.socket.write(IOBuf(bytes([RTMP_VERSION]) + c1))

    def send_message(self, csid: int, msid: int, mtype: int,
                     timestamp: int, body: bytes) -> int:
        """Chunk one message onto the wire: fmt-0 header + fmt-3
        continuations (always-absolute timestamps keep the sender simple;
        receivers must support all fmts regardless)."""
        ts = timestamp & 0xFFFFFFFF
        ext = ts >= _TIMESTAMP_MASK
        hdr_ts = _TIMESTAMP_MASK if ext else ts
        out = bytearray()
        out += self._basic_header(0, csid)
        out += _p24(hdr_ts) + _p24(len(body)) + bytes([mtype]) \
            + struct.pack("<I", msid)
        if ext:
            out += struct.pack(">I", ts)
        off = 0
        n = len(body)
        with self._out_lock:                 # message-atomic chunking
            chunk = self.out_chunk_size
            take = min(chunk, n - off)
            out += body[off:off + take]
            off += take
            while off < n:
                out += self._basic_header(3, csid)
                if ext:
                    out += struct.pack(">I", ts)
                take = min(chunk, n - off)
                out += body[off:off + take]
                off += take
            return self.socket.write(IOBuf(bytes(out)))

    @staticmethod
    def _basic_header(fmt: int, csid: int) -> bytes:
        if csid < 64:
            return bytes([(fmt << 6) | csid])
        if csid < 320:
            return bytes([(fmt << 6), csid - 64])
        return bytes([(fmt << 6) | 1]) + struct.pack("<H", csid - 64)

    def _send_control(self, mtype: int, body: bytes) -> None:
        self.send_message(CSID_CONTROL, 0, mtype, 0, body)

    def _send_command(self, csid: int, msid: int, *vals: Any) -> None:
        self.send_message(csid, msid, MSG_COMMAND_AMF0, 0,
                          amf.encode(*vals))

    def _send_status(self, msid: int, level: str, code: str,
                     description: str) -> int:
        info = {"level": level, "code": code, "description": description}
        return self.send_message(CSID_STATUS, msid, MSG_COMMAND_AMF0, 0,
                                 amf.encode("onStatus", 0.0, None, info))

    def set_out_chunk_size(self, size: int) -> None:
        # announce + apply atomically w.r.t. concurrent senders (the lock
        # is reentrant: send_message chunks under it too), so no message
        # can be chunked with the old size after the peer switched
        with self._out_lock:
            self._send_control(MSG_SET_CHUNK_SIZE, struct.pack(">I", size))
            self.out_chunk_size = size

    # ---- client transactions ------------------------------------------

    def call_command(self, name: str, *args: Any, timeout: float = 5.0):
        """Send a transaction-numbered NetConnection command and wait for
        its _result (client side)."""
        with self._pending_lock:
            txn = self._next_txn
            self._next_txn += 1
            ev = CountdownEvent(1)
            box: List[Any] = []
            self._pending[txn] = (ev, box)
        self._send_command(CSID_COMMAND, 0, name, float(txn), *args)
        if ev.wait(timeout) != 0:
            with self._pending_lock:
                self._pending.pop(txn, None)
            return None, errors.ERPCTIMEDOUT
        if not box or box[0] == "_error":
            return None, errors.EREQUEST
        return box[1:], 0

    # ---- inbound -------------------------------------------------------

    def consume(self, source: IOBuf) -> bool:
        """Drain everything processable from the read buffer; returns
        False on a protocol error (connection must die)."""
        try:
            while True:
                before = len(source)
                if self.state != _ESTABLISHED:
                    if not self._consume_handshake(source):
                        return True if not self.socket.failed else False
                else:
                    if not self._consume_chunk(source):
                        return True
                consumed = before - len(source)
                self.in_bytes_total += consumed
                self.in_bytes_unacked += consumed
                if self.in_bytes_unacked >= self.ack_window:
                    self._send_control(
                        MSG_ACK, struct.pack(
                            ">I", self.in_bytes_total & 0xFFFFFFFF))
                    self.in_bytes_unacked = 0
                if consumed == 0:
                    return True
        except (amf.AmfError, struct.error, ValueError) as e:
            log.error("rtmp protocol error: %s", e)
            return False

    def _consume_handshake(self, source: IOBuf) -> bool:
        if self.state == _HS_WAIT_C0C1:
            data = source.fetch(1 + HANDSHAKE_SIZE)
            if data is None:
                return False
            if data[0] != RTMP_VERSION:
                raise ValueError(f"bad RTMP version {data[0]}")
            source.pop_front(1 + HANDSHAKE_SIZE)
            c1 = data[1:]
            # digest auto-detection (rtmp_protocol.cpp: try the complex
            # handshake, fall back to simple): a C1 whose HMAC validates
            # under either scheme gets a digest-mode S1 + keyed S2; a
            # plain C1 gets the simple echo
            c1_digest = find_handshake_digest(c1)
            if c1_digest is not None:
                s1 = make_digest_block(_S1_VERSION, _FMS_KEY[:36])
                s2 = make_handshake_response2(c1_digest, _FMS_KEY)
            else:
                s1 = struct.pack(">II", 0, 0) \
                    + os.urandom(HANDSHAKE_SIZE - 8)
                s2 = c1
            self.socket.write(IOBuf(bytes([RTMP_VERSION]) + s1 + s2))
            self.state = _HS_WAIT_C2
            return True
        if self.state == _HS_WAIT_C2:
            if source.fetch(HANDSHAKE_SIZE) is None:
                return False
            source.pop_front(HANDSHAKE_SIZE)
            self.state = _ESTABLISHED
            return True
        if self.state == _HS_WAIT_S0S1S2:
            data = source.fetch(1 + 2 * HANDSHAKE_SIZE)
            if data is None:
                return False
            if data[0] != RTMP_VERSION:
                raise ValueError(f"bad RTMP version {data[0]}")
            source.pop_front(1 + 2 * HANDSHAKE_SIZE)
            s1 = data[1:1 + HANDSHAKE_SIZE]
            s2 = data[1 + HANDSHAKE_SIZE:]
            c2 = s1                             # simple: C2 echoes S1
            if self._c1_digest is not None:
                # digest mode: validate the server's proof-of-read,
                # then key C2 on ITS digest.  A simple-handshake server
                # (no valid S1 digest) downgrades us gracefully — the
                # reference proceeds the same way
                s1_digest = find_handshake_digest(s1, _FMS_KEY[:36])
                if s1_digest is not None:
                    if not validate_handshake_response2(
                            s2, self._c1_digest, _FMS_KEY):
                        raise ValueError("rtmp digest handshake: S2 "
                                         "proof-of-read invalid")
                    c2 = make_handshake_response2(s1_digest, _FP_KEY)
                else:
                    log.warning("rtmp: digest C1 answered by a "
                                "simple-handshake server; downgrading")
            self.socket.write(IOBuf(c2))
            self.state = _ESTABLISHED
            self._on_client_established()
            return True
        return False

    def _consume_chunk(self, source: IOBuf) -> bool:
        """Parse exactly one chunk if fully buffered (returns False to
        wait for more bytes)."""
        b0 = source.fetch(1)
        if b0 is None:
            return False
        fmt = b0[0] >> 6
        csid = b0[0] & 0x3F
        bh_len = 1
        if csid == 0:
            hdr = source.fetch(2)
            if hdr is None:
                return False
            csid = 64 + hdr[1]
            bh_len = 2
        elif csid == 1:
            hdr = source.fetch(3)
            if hdr is None:
                return False
            csid = 64 + hdr[1] + (hdr[2] << 8)
            bh_len = 3
        cs = self._in_streams.get(csid)
        if cs is None:
            cs = self._in_streams[csid] = _InChunkState()
        mh_len = (11, 7, 3, 0)[fmt]
        head = source.fetch(bh_len + mh_len)
        if head is None:
            return False
        mh = head[bh_len:]
        # provisional header decode to learn ext-ts presence
        ext = cs.has_ext_ts if fmt == 3 else (_u24(mh) >= _TIMESTAMP_MASK)
        ext_len = 4 if ext else 0
        new_message = cs.msg_remaining == 0
        if new_message:
            if fmt == 0:
                msg_len = _u24(mh, 3)
            elif fmt in (1, 2):
                msg_len = _u24(mh, 3) if fmt == 1 else cs.msg_len
            else:
                msg_len = cs.msg_len
            take = min(self.in_chunk_size, msg_len)
        else:
            if fmt != 3:
                raise ValueError(
                    f"chunk fmt {fmt} inside a partial message (csid "
                    f"{csid})")
            take = min(self.in_chunk_size, cs.msg_remaining)
        total = bh_len + mh_len + ext_len + take
        data = source.fetch(total)
        if data is None:
            return False
        source.pop_front(total)
        if ext:
            ts_field = struct.unpack_from(">I", data, bh_len + mh_len)[0]
        elif fmt != 3:
            ts_field = _u24(mh)
        else:
            ts_field = 0                    # fmt3 carries no timestamp
        if new_message:
            if fmt == 0:
                cs.timestamp = ts_field
                cs.ts_delta = 0
                cs.msg_len = _u24(mh, 3)
                cs.msg_type = mh[6]
                cs.msid = struct.unpack_from("<I", mh, 7)[0]
            elif fmt == 1:
                cs.ts_delta = ts_field
                cs.timestamp = (cs.timestamp + ts_field) & 0xFFFFFFFF
                cs.msg_len = _u24(mh, 3)
                cs.msg_type = mh[6]
            elif fmt == 2:
                cs.ts_delta = ts_field
                cs.timestamp = (cs.timestamp + ts_field) & 0xFFFFFFFF
            else:
                cs.timestamp = (cs.timestamp + cs.ts_delta) & 0xFFFFFFFF
            cs.has_ext_ts = ext
            if cs.msg_len > _MAX_MESSAGE_SIZE:
                raise ValueError(f"rtmp message too large: {cs.msg_len}")
            cs.msg_remaining = cs.msg_len
            cs.partial = bytearray()
        payload = data[bh_len + mh_len + ext_len:]
        cs.partial += payload
        cs.msg_remaining -= len(payload)
        if cs.msg_remaining == 0 and (cs.msg_len == 0 or cs.partial):
            msg = RtmpMessage(cs.msg_type, cs.timestamp, cs.msid,
                              bytes(cs.partial))
            cs.partial = bytearray()
            self._dispatch(msg)
        return True

    # ---- message dispatch ---------------------------------------------

    def _dispatch(self, msg: RtmpMessage) -> None:
        t = msg.type
        if t == MSG_SET_CHUNK_SIZE:
            if len(msg.body) >= 4:
                self.in_chunk_size = max(
                    1, struct.unpack(">I", msg.body[:4])[0] & 0x7FFFFFFF)
        elif t == MSG_ABORT:
            if len(msg.body) >= 4:
                csid = struct.unpack(">I", msg.body[:4])[0]
                cs = self._in_streams.get(csid)
                if cs is not None:
                    cs.partial = bytearray()
                    cs.msg_remaining = 0
        elif t == MSG_ACK:
            pass
        elif t == MSG_WINDOW_ACK_SIZE:
            if len(msg.body) >= 4:
                self.ack_window = max(
                    1, struct.unpack(">I", msg.body[:4])[0])
        elif t == MSG_SET_PEER_BANDWIDTH:
            pass
        elif t == MSG_USER_CONTROL:
            self._on_user_control(msg)
        elif t in (MSG_COMMAND_AMF0, MSG_COMMAND_AMF3):
            body = msg.body
            if t == MSG_COMMAND_AMF3 and body[:1] == b"\x00":
                body = body[1:]          # AMF3 envelope: format selector
            vals = amf.decode_all(body)
            if vals:
                self._on_command(msg, vals)
        elif t in (MSG_DATA_AMF0, MSG_DATA_AMF3):
            body = msg.body
            if t == MSG_DATA_AMF3 and body[:1] == b"\x00":
                body = body[1:]
            self._on_data(msg, amf.decode_all(body))
        elif t == MSG_AUDIO:
            s = self._streams.get(msg.msid)
            if s is not None:
                s._deliver(s.on_audio_message, msg.timestamp, msg.body)
        elif t == MSG_VIDEO:
            s = self._streams.get(msg.msid)
            if s is not None:
                s._deliver(s.on_video_message, msg.timestamp, msg.body)
        elif t == MSG_AGGREGATE:
            self._split_aggregate(msg)

    def _on_user_control(self, msg: RtmpMessage) -> None:
        if len(msg.body) < 2:
            return
        ev = struct.unpack(">H", msg.body[:2])[0]
        data = msg.body[2:]
        if ev == UC_PING_REQUEST:
            self._send_control(MSG_USER_CONTROL,
                               struct.pack(">H", UC_PING_RESPONSE) + data)
            return
        if len(data) >= 4:
            msid = struct.unpack(">I", data[:4])[0]
            s = self._streams.get(msid)
            if s is not None:
                s._deliver(s.on_user_control, ev, data)

    def _split_aggregate(self, msg: RtmpMessage) -> None:
        """Aggregate body = FLV-style tags (type,size,ts,msid) each
        followed by a 4-byte back-pointer (rtmp_protocol.cpp aggregate
        handling)."""
        body = msg.body
        off = 0
        base_ts: Optional[int] = None
        while off + 11 <= len(body):
            ttype = body[off]
            size = _u24(body, off + 1)
            ts = _u24(body, off + 4) | (body[off + 7] << 24)
            if off + 11 + size + 4 > len(body):
                break
            if base_ts is None:
                base_ts = ts
            sub_ts = (msg.timestamp + (ts - base_ts)) & 0xFFFFFFFF
            sub = RtmpMessage(ttype, sub_ts, msg.msid,
                              body[off + 11:off + 11 + size])
            self._dispatch(sub)
            off += 11 + size + 4

    def _on_data(self, msg: RtmpMessage, vals: List[Any]) -> None:
        if not vals:
            return
        name = vals[0] if isinstance(vals[0], str) else ""
        rest = vals[1:]
        if name == "@setDataFrame" and rest:      # publisher relays meta
            name = rest[0] if isinstance(rest[0], str) else name
            rest = rest[1:]
        meta = next((v for v in rest if isinstance(v, dict)), None)
        s = self._streams.get(msg.msid)
        if s is not None and meta is not None:
            s._deliver(s.on_meta_data, dict(meta), name)

    # ---- command handling ---------------------------------------------

    def _on_command(self, msg: RtmpMessage, vals: List[Any]) -> None:
        name = vals[0] if isinstance(vals[0], str) else ""
        if self.is_server:
            self._on_server_command(msg, name, vals)
        else:
            self._on_client_command(msg, name, vals)

    def _txn(self, vals: List[Any]) -> float:
        return float(vals[1]) if len(vals) > 1 and isinstance(
            vals[1], (int, float)) else 0.0

    def _on_server_command(self, msg: RtmpMessage, name: str,
                           vals: List[Any]) -> None:
        txn = self._txn(vals)
        if name == "connect":
            if len(vals) > 2 and isinstance(vals[2], dict):
                self.connect_info = dict(vals[2])
            self._send_control(MSG_WINDOW_ACK_SIZE,
                               struct.pack(">I", DEFAULT_WINDOW_ACK_SIZE))
            self._send_control(MSG_SET_PEER_BANDWIDTH,
                               struct.pack(">IB", DEFAULT_WINDOW_ACK_SIZE,
                                           2))
            self.set_out_chunk_size(OUT_CHUNK_SIZE)
            self._send_control(MSG_USER_CONTROL,
                               struct.pack(">HI", UC_STREAM_BEGIN, 0))
            self._send_command(
                CSID_COMMAND, 0, "_result", txn,
                {"fmsVer": "FMS/3,5,3,824", "capabilities": 127.0},
                {"level": "status",
                 "code": "NetConnection.Connect.Success",
                 "description": "Connection succeeded.",
                 "objectEncoding": 0.0})
        elif name == "createStream":
            with self._streams_lock:
                msid = self._next_msid
                self._next_msid += 1
            self._send_command(CSID_COMMAND, 0, "_result", txn, None,
                               float(msid))
        elif name in ("releaseStream", "FCPublish", "FCUnpublish",
                      "getStreamLength"):
            self._send_command(CSID_COMMAND, 0, "_result", txn, None,
                               amf.UNDEFINED)
        elif name == "publish":
            sname = vals[3] if len(vals) > 3 and isinstance(vals[3], str) \
                else ""
            ptype = vals[4] if len(vals) > 4 and isinstance(vals[4], str) \
                else "live"
            self._server_open_stream(msg.msid, "publish", sname, ptype)
        elif name == "play":
            sname = vals[3] if len(vals) > 3 and isinstance(vals[3], str) \
                else ""
            self._server_open_stream(msg.msid, "play", sname, "")
        elif name == "deleteStream":
            msid = int(vals[3]) if len(vals) > 3 and isinstance(
                vals[3], (int, float)) else 0
            self._drop_stream(msid, notify=True)
        elif name == "closeStream":
            self._drop_stream(msg.msid, notify=True)
        # unknown commands are ignored (the reference logs and continues)

    def _server_open_stream(self, msid: int, what: str, sname: str,
                            ptype: str) -> None:
        svc = getattr(self.server, "_rtmp_service", None)
        if svc is None or msid == 0:
            self._send_status(msid, "error", "NetStream.Failed",
                              "no rtmp service")
            return
        with self._streams_lock:
            stream = self._streams.get(msid)
            if stream is None:
                stream = svc.new_stream(self.socket.remote_side,
                                        self.connect_info)
                stream._conn = self
                stream.stream_id = msid
                stream.remote_side = self.socket.remote_side
                self._streams[msid] = stream

        def accept():
            if what == "publish":
                rc = stream.on_publish(sname, ptype)
                if rc == 0:
                    stream.publish_name = sname
                    self._send_status(msid, "status",
                                      "NetStream.Publish.Start",
                                      f"Publishing {sname}.")
                else:
                    self._send_status(msid, "error",
                                      "NetStream.Publish.BadName",
                                      f"Rejected {sname}.")
            else:
                rc = stream.on_play(sname)
                if rc == 0:
                    stream.play_name = sname
                    self._send_control(
                        MSG_USER_CONTROL,
                        struct.pack(">HI", UC_STREAM_BEGIN, msid))
                    self._send_status(msid, "status",
                                      "NetStream.Play.Reset",
                                      f"Resetting {sname}.")
                    self._send_status(msid, "status",
                                      "NetStream.Play.Start",
                                      f"Started playing {sname}.")
                else:
                    self._send_status(msid, "error",
                                      "NetStream.Play.StreamNotFound",
                                      f"No stream {sname}.")
        stream._deliver(accept)          # ordered before subsequent AV

    def _on_client_command(self, msg: RtmpMessage, name: str,
                           vals: List[Any]) -> None:
        if name in ("_result", "_error"):
            txn = int(self._txn(vals))
            with self._pending_lock:
                pending = self._pending.pop(txn, None)
            if pending is not None:
                ev, box = pending
                box.append(name)
                box.extend(vals[2:])
                ev.signal()
            elif txn == 1:               # the connect transaction
                self.connect_error = 0 if name == "_result" else \
                    errors.EREQUEST
                self.connected.signal()
        elif name == "onStatus":
            info = next((v for v in vals[2:] if isinstance(v, dict)), {})
            s = self._streams.get(msg.msid)
            if isinstance(s, RtmpClientStream):
                s._on_status(dict(info))
        elif name == "onBWDone":
            pass

    def _on_client_established(self) -> None:
        """Handshake finished (client): send connect(txn=1)."""
        self.set_out_chunk_size(OUT_CHUNK_SIZE)
        info = dict(self._connect_request)
        self._send_command(CSID_COMMAND, 0, "connect", 1.0, info)

    # ---- lifecycle -----------------------------------------------------

    def _drop_stream(self, msid: int, notify: bool = False) -> None:
        with self._streams_lock:
            s = self._streams.pop(msid, None)
        if s is not None and notify:
            s._shutdown()

    def _on_socket_failed(self, socket) -> None:
        self.connect_error = self.connect_error or errors.EFAILEDSOCKET
        self.connected.signal()
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for ev, box in pending:
            box.append("_error")
            ev.signal()
        with self._streams_lock:
            streams = list(self._streams.values())
            self._streams.clear()
        for s in streams:
            if isinstance(s, RtmpClientStream):
                s._on_status({"level": "error",
                              "code": "NetConnection.Closed",
                              "description": "connection lost"})
            s._shutdown()


# ---- client ------------------------------------------------------------

class RtmpClientOptions:
    def __init__(self, app: str = "live", tc_url: str = "",
                 flash_ver: str = "brpc_tpu/1.0", swf_url: str = "",
                 page_url: str = "", timeout: float = 5.0):
        self.app = app
        self.tc_url = tc_url
        self.flash_ver = flash_ver
        self.swf_url = swf_url
        self.page_url = page_url
        self.timeout = timeout


class RtmpClient:
    """NetConnection owner (rtmp.h RtmpClient:880-940): one TCP+RTMP
    connection; create_stream() yields RtmpClientStream handles."""

    def __init__(self, address: Any,
                 options: Optional[RtmpClientOptions] = None):
        self.options = options or RtmpClientOptions()
        ep = address if isinstance(address, EndPoint) else \
            parse_endpoint(address if "://" in str(address)
                           else f"tcp://{address}")
        from ..rpc.input_messenger import InputMessenger
        from ..rpc.tcp_transport import tcp_connect
        self._socket = tcp_connect(ep, timeout=self.options.timeout)
        self._socket.messenger = InputMessenger(protocols=[RTMP_PROTOCOL])
        conn = RtmpConnection(self._socket, is_server=False)
        tc_url = self.options.tc_url or \
            f"rtmp://{ep.host}:{ep.port}/{self.options.app}"
        conn._connect_request = {
            "app": self.options.app,
            "flashVer": self.options.flash_ver,
            "swfUrl": self.options.swf_url,
            "tcUrl": tc_url,
            "fpad": False,
            "audioCodecs": 3575.0,
            "videoCodecs": 252.0,
            "videoFunction": 1.0,
            "pageUrl": self.options.page_url,
            "objectEncoding": 0.0,
        }
        self._conn = conn
        self._socket._rtmp_conn = conn
        conn._start_client_handshake()
        if conn.connected.wait(self.options.timeout) != 0:
            self._socket.set_failed(errors.ERPCTIMEDOUT, "rtmp connect")
            raise TimeoutError("RTMP connect timed out")
        if conn.connect_error:
            self._socket.set_failed(conn.connect_error, "rtmp connect")
            raise ConnectionError(
                f"RTMP connect failed: {errors.berror(conn.connect_error)}")

    def create_stream(self, stream: Optional[RtmpClientStream] = None,
                      timeout: float = 5.0) -> RtmpClientStream:
        result, err = self._conn.call_command("createStream", None,
                                              timeout=timeout)
        if err or not result or not isinstance(result[-1], (int, float)):
            raise ConnectionError("createStream failed")
        msid = int(result[-1])
        s = stream or RtmpClientStream()
        s._conn = self._conn
        s.stream_id = msid
        with self._conn._streams_lock:
            self._conn._streams[msid] = s
        return s

    @property
    def connect_info(self) -> Dict[str, Any]:
        return self._conn.connect_info

    def stop(self) -> None:
        self._socket.set_failed(errors.ECLOSE, "client stopped")


# ---- protocol registration ---------------------------------------------

def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    conn = getattr(socket, "_rtmp_conn", None)
    if conn is None:
        server = getattr(arg, "server", None)
        if server is None or getattr(server, "_rtmp_service", None) is None:
            return ParseResult.try_others()
        first = source.fetch1()
        if first is None:
            return ParseResult.not_enough_data()
        if first != RTMP_VERSION:
            return ParseResult.try_others()
        # C0 alone is ambiguous with very short binary frames; require C1
        # to begin arriving before claiming the connection
        if len(source) < 2:
            return ParseResult.not_enough_data()
        conn = RtmpConnection(socket, is_server=True, server=server)
        socket._rtmp_conn = conn
    if not conn.consume(source):
        return ParseResult.parse_error("rtmp protocol error")
    return ParseResult.not_enough_data()


RTMP_PROTOCOL = Protocol(
    name="rtmp",
    parse=parse,
    supported_connection_type=CONNECTION_TYPE_SINGLE,
    support_client=True,
    support_server=True,
)


def _register() -> None:
    from ..rpc.protocol import find_protocol
    if find_protocol("rtmp") is None:
        register_protocol(RTMP_PROTOCOL)


_register()
