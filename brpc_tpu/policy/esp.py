"""esp: packed-head message protocol (legacy UB ecosystem peer).

Reference behavior: src/brpc/esp_head.h (packed 32-byte head: from/to
addresses as u64 unions, msg, msg_id, body_len), src/brpc/esp_message.h
(EspMessage = head + raw body), src/brpc/policy/esp_protocol.cpp (client
side only; no correlation field → id stashed per connection, pooled/short
connections).  The head has no magic, so parse only claims bytes when an
esp call is outstanding on the socket — the same defensive gating the
memcache client uses here.

Extension beyond the reference: a minimal EspService raw server so the
protocol round-trips in-process (the reference can only test against
external esp servers).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..butil.iobuf import IOBuf
from ..butil import logging as log
from ..bthread import id as bthread_id
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import (CONNECTION_TYPE_POOLED, CONNECTION_TYPE_SHORT,
                            Protocol, ParseResult, register_protocol,
                            find_protocol)

_HEAD = struct.Struct("<QQIQi")       # from to msg msg_id body_len
HEAD_SIZE = _HEAD.size                # 32


@dataclass
class EspHead:
    from_addr: int = 0
    to_addr: int = 0
    msg: int = 0
    msg_id: int = 0
    body_len: int = 0

    def pack(self) -> bytes:
        return _HEAD.pack(self.from_addr, self.to_addr, self.msg,
                          self.msg_id, self.body_len)

    @staticmethod
    def unpack(raw: bytes) -> "EspHead":
        f, t, m, mid, blen = _HEAD.unpack(raw[:HEAD_SIZE])
        return EspHead(f, t, m, mid, blen)


class EspMessage:
    __slots__ = ("head", "body")

    def __init__(self, head: Optional[EspHead] = None,
                 body: Optional[IOBuf] = None):
        self.head = head or EspHead()
        self.body = body if body is not None else IOBuf()

    def pack(self) -> IOBuf:
        self.head.body_len = len(self.body)
        out = IOBuf()
        out.append(self.head.pack())
        out.append(self.body)
        return out


class EspService:
    """Raw esp server handler: override process_esp_request, call done()."""

    SERVICE_NAME = "esp"

    def process_esp_request(self, server, controller: Controller,
                            request: EspMessage, response: EspMessage,
                            done: Callable[[], None]) -> None:
        raise NotImplementedError


class _EspCtx:
    __slots__ = ("cid",)

    def __init__(self, cid: int):
        self.cid = cid


def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    server = getattr(arg, "server", None)
    if server is not None:
        if getattr(server, "_esp_service", None) is None:
            return ParseResult.try_others()
    else:
        ctxs = getattr(socket, "pipelined_contexts", None)
        if not ctxs or not isinstance(ctxs[0], _EspCtx):
            return ParseResult.try_others()
    head_raw = source.fetch(HEAD_SIZE)
    if head_raw is None:
        return ParseResult.not_enough_data()
    head = EspHead.unpack(head_raw)
    # the esp head has no magic: cap body_len tightly so garbage bytes on
    # a server hosting an EspService fail the connection rather than
    # stalling it waiting for gigabytes that will never arrive
    if head.body_len < 0 or head.body_len > (16 << 20):
        return ParseResult.parse_error("absurd esp body_len")
    if len(source) < HEAD_SIZE + head.body_len:
        return ParseResult.not_enough_data()
    source.pop_front(HEAD_SIZE)
    body = source.cut(head.body_len)
    return ParseResult.ok(EspMessage(head, body))


def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    if not isinstance(request, EspMessage):
        raise TypeError("esp request must be an EspMessage")
    cntl._esp_head = request.head
    buf = IOBuf()
    buf.append(request.body)
    return buf


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    head: EspHead = getattr(cntl, "_esp_head", None) or EspHead()
    head.body_len = len(payload)
    out = IOBuf()
    out.append(head.pack())
    out.append(payload)
    return out


def make_pipeline_ctx(cid: int, cntl: Controller) -> _EspCtx:
    return _EspCtx(cid)


def process_response(msg: EspMessage, socket) -> None:
    ctx = socket.pop_pipelined_context()
    if ctx is None or not isinstance(ctx, _EspCtx):
        log.warning("esp response with no outstanding call; dropped")
        return
    rc, cntl = bthread_id.lock(ctx.cid)
    if rc != 0 or cntl is None:
        return
    cntl.remote_side = socket.remote_side
    cntl.response = msg
    cntl.finish_parsed_response(ctx.cid)


def process_request(msg: EspMessage, socket, server) -> None:
    svc = getattr(server, "_esp_service", None)
    if svc is None:
        socket.set_failed(errors.ENOSERVICE, "no esp service")
        return
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = socket.remote_side
    response = EspMessage()
    response.head = EspHead(from_addr=msg.head.to_addr,
                            to_addr=msg.head.from_addr,
                            msg=msg.head.msg, msg_id=msg.head.msg_id)
    fired = [False]
    counted = [False]

    def done() -> None:
        if fired[0]:
            return
        fired[0] = True
        socket.write(response.pack())
        if counted[0]:
            server.on_request_out()

    if not server.on_request_in():
        cntl.set_failed(errors.ELIMIT, "server max_concurrency reached")
        done()
        return
    counted[0] = True
    try:
        svc.process_esp_request(server, cntl, msg, response, done)
    except Exception as e:
        log.error("esp service raised: %s", e, exc_info=True)
        if not fired[0]:
            done()


PROTOCOL = Protocol(
    name="esp",
    parse=parse,
    process_request=process_request,
    process_response=process_response,
    serialize_request=serialize_request,
    pack_request=pack_request,
    supported_connection_type=CONNECTION_TYPE_POOLED | CONNECTION_TYPE_SHORT,
    pipelined=True,
    make_pipeline_ctx=make_pipeline_ctx,
)


if find_protocol("esp") is None:
    register_protocol(PROTOCOL)
