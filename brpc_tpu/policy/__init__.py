"""policy — pluggable protocols, LBs, limiters, naming (reference:
src/brpc/policy/, SURVEY.md §2.5).  Importing this package registers the
default protocol set (the reference does this in global.cpp:354-581)."""
from . import tpu_std
from . import limiters
from . import load_balancers
from . import naming
from . import http
from . import redis
from . import memcache
from . import mongo
from . import thrift
from . import auth
from . import grpc
from . import nshead
from . import legacy_pbrpc
from . import nova
from . import public_pbrpc
from . import esp
from . import ubrpc
from . import amf
from . import rtmp
