"""Mongo wire protocol: OP_MSG/OP_QUERY server adaptor + client.

Reference: src/brpc/policy/mongo_protocol.cpp (298 L), src/brpc/mongo_head.h
(16-byte little-endian head: message_length, request_id, response_to,
op_code; `is_mongo_opcode` gate at mongo_head.h:40),
src/brpc/mongo_service_adaptor.h — the reference hands the raw message to a
user adaptor and leaves BSON to user code.  This build keeps that adaptor
shape (``MongoService.process``) and additionally ships a minimal BSON
codec so the adaptor is usable without external drivers (none in the
image).

Client:
    ch.init(target, options=ChannelOptions(protocol="mongo"))
    req = MongoRequest({"ping": 1, "$db": "admin"})
    resp = ch.call_method("mongo", cntl, req, MongoResponse)
    resp.doc   # decoded BSON reply document

Server:
    class MyMongo(MongoService):
        def process(self, cntl, doc):     # doc: decoded request document
            return {"ok": 1}
    server.add_mongo_service(MyMongo())   # via Server.add_service too
"""
from __future__ import annotations

import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..bthread import id as bthread_id
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import (Protocol, ParseResult, register_protocol)

# ---- opcodes (mongo_head.h:27-58) -------------------------------------

OP_REPLY = 1
OP_UPDATE = 2001
OP_INSERT = 2002
OP_QUERY = 2004
OP_GET_MORE = 2005
OP_DELETE = 2006
OP_KILL_CURSORS = 2007
OP_COMPRESSED = 2012
OP_MSG = 2013

_KNOWN_OPCODES = {OP_REPLY, OP_UPDATE, OP_INSERT, OP_QUERY, OP_GET_MORE,
                  OP_DELETE, OP_KILL_CURSORS, OP_COMPRESSED, OP_MSG}

HEAD_SIZE = 16
_MAX_MESSAGE = 48 * 1024 * 1024     # mongo's maxMessageSizeBytes


class MongoHead:
    """16-byte little-endian message head (mongo_head.h:60-78)."""
    __slots__ = ("message_length", "request_id", "response_to", "op_code")

    def __init__(self, message_length=0, request_id=0, response_to=0,
                 op_code=OP_MSG):
        self.message_length = message_length
        self.request_id = request_id
        self.response_to = response_to
        self.op_code = op_code

    def pack(self) -> bytes:
        return struct.pack("<iiii", self.message_length, self.request_id,
                           self.response_to, self.op_code)

    @staticmethod
    def unpack(data: bytes) -> "MongoHead":
        ml, rid, rto, op = struct.unpack("<iiii", data[:HEAD_SIZE])
        return MongoHead(ml, rid, rto, op)


# ---- minimal BSON codec -----------------------------------------------
# Types: double, string, document, array, binary, bool, null, int32,
# int64 — the working set for command documents.  (The reference ships no
# BSON at all; this is a usability addition, not a parity requirement.)

def _bson_encode_value(name: bytes, v: Any) -> bytes:
    if isinstance(v, bool):                       # before int check!
        return b"\x08" + name + b"\x00" + (b"\x01" if v else b"\x00")
    if isinstance(v, float):
        return b"\x01" + name + b"\x00" + struct.pack("<d", v)
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + name + b"\x00" + struct.pack("<i", v)
        return b"\x12" + name + b"\x00" + struct.pack("<q", v)
    if isinstance(v, str):
        enc = v.encode() + b"\x00"
        return b"\x02" + name + b"\x00" + struct.pack("<i", len(enc)) + enc
    if isinstance(v, (bytes, bytearray)):
        return (b"\x05" + name + b"\x00" + struct.pack("<i", len(v))
                + b"\x00" + bytes(v))             # subtype 0 generic
    if v is None:
        return b"\x0a" + name + b"\x00"
    if isinstance(v, dict):
        return b"\x03" + name + b"\x00" + bson_encode(v)
    if isinstance(v, (list, tuple)):
        doc = {str(i): x for i, x in enumerate(v)}
        return b"\x04" + name + b"\x00" + bson_encode(doc)
    raise TypeError(f"BSON cannot encode {type(v)}")


def bson_encode(doc: Dict[str, Any]) -> bytes:
    body = b"".join(_bson_encode_value(k.encode(), v)
                    for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _bson_decode_doc(data: bytes, off: int) -> Tuple[Dict[str, Any], int]:
    (total,) = struct.unpack_from("<i", data, off)
    end = off + total - 1                 # trailing NUL
    off += 4
    out: Dict[str, Any] = {}
    while off < end:
        t = data[off]
        off += 1
        nul = data.index(b"\x00", off)
        name = data[off:nul].decode()
        off = nul + 1
        if t == 0x01:
            (out[name],) = struct.unpack_from("<d", data, off); off += 8
        elif t == 0x02:
            (n,) = struct.unpack_from("<i", data, off); off += 4
            out[name] = data[off:off + n - 1].decode(); off += n
        elif t == 0x03:
            out[name], off = _bson_decode_doc(data, off)
        elif t == 0x04:
            sub, off = _bson_decode_doc(data, off)
            out[name] = [sub[str(i)] for i in range(len(sub))]
        elif t == 0x05:
            (n,) = struct.unpack_from("<i", data, off); off += 5  # +subtype
            out[name] = data[off:off + n]; off += n
        elif t == 0x08:
            out[name] = data[off] != 0; off += 1
        elif t == 0x09:                    # UTC datetime: surface as int64 ms
            (out[name],) = struct.unpack_from("<q", data, off); off += 8
        elif t == 0x0a:
            out[name] = None
        elif t == 0x10:
            (out[name],) = struct.unpack_from("<i", data, off); off += 4
        elif t == 0x11 or t == 0x12:       # timestamp / int64
            (out[name],) = struct.unpack_from("<q", data, off); off += 8
        else:
            raise ValueError(f"BSON type 0x{t:02x} unsupported")
    return out, end + 1


def bson_decode(data: bytes) -> Dict[str, Any]:
    doc, _ = _bson_decode_doc(bytes(data), 0)
    return doc


# ---- OP_MSG body ------------------------------------------------------

def _pack_op_msg(doc: Dict[str, Any], flags: int = 0) -> bytes:
    return struct.pack("<I", flags) + b"\x00" + bson_encode(doc)


def _parse_op_msg(body: bytes) -> Dict[str, Any]:
    """Parse an OP_MSG body: kind-0 section is the command document;
    kind-1 document sequences are folded in as a list under their name."""
    (flags,) = struct.unpack_from("<I", body, 0)
    off = 4
    doc: Dict[str, Any] = {}
    if flags & 0x1:                        # checksumPresent: ignore CRC tail
        body = body[:-4]
    while off < len(body):
        kind = body[off]
        off += 1
        if kind == 0:
            d, off = _bson_decode_doc(body, off)
            doc.update(d)
        elif kind == 1:
            (sec_len,) = struct.unpack_from("<i", body, off)
            sec_end = off + sec_len
            p = off + 4
            nul = body.index(b"\x00", p)
            name = body[p:nul].decode()
            p = nul + 1
            docs: List[Dict[str, Any]] = []
            while p < sec_end:
                d, p = _bson_decode_doc(body, p)
                docs.append(d)
            doc[name] = docs
            off = sec_end
        else:
            raise ValueError(f"OP_MSG section kind {kind}")
    return doc


class MongoMessage:
    __slots__ = ("head", "body")

    def __init__(self, head: MongoHead, body: bytes):
        self.head = head
        self.body = body

    @property
    def doc(self) -> Dict[str, Any]:
        if self.head.op_code == OP_MSG:
            return _parse_op_msg(self.body)
        if self.head.op_code == OP_QUERY:
            # flags(4) + cstring collection + skip(4) + limit(4) + doc
            off = 4
            off = self.body.index(b"\x00", off) + 1
            off += 8
            d, _ = _bson_decode_doc(self.body, off)
            return d
        raise ValueError(f"cannot decode opcode {self.head.op_code}")


# ---- request/response value types -------------------------------------

class MongoRequest:
    def __init__(self, doc: Dict[str, Any], op_code: int = OP_MSG):
        self.doc = doc
        self.op_code = op_code


class MongoResponse:
    def __init__(self):
        self.doc: Dict[str, Any] = {}
        self.head: Optional[MongoHead] = None


# ---- server adaptor (mongo_service_adaptor.h equivalent) ---------------

class MongoService:
    """Subclass and override process(); register on a Server.  The server
    dispatches every mongo message here (there is no method routing in the
    mongo wire protocol — the command is inside the document)."""

    SERVICE_NAME = "mongo"

    def methods(self):                     # Server.add_service compatibility
        return {}

    def process(self, cntl: Controller, doc: Dict[str, Any]
                ) -> Optional[Dict[str, Any]]:
        raise NotImplementedError


# ---- correlation: request_id(int32) → versioned cid --------------------

_corr_lock = threading.Lock()
_corr: Dict[int, Tuple[int, float]] = {}    # rid -> (cid, expiry)
_next_req_id = [1]
_CORR_TTL = 130.0        # > any sane rpc timeout; sweeps dead entries
_SWEEP_EVERY = 256
_calls_since_sweep = [0]


def _new_request_id(cid: int, ttl: Optional[float] = None) -> int:
    import time as _time
    now = _time.monotonic()
    with _corr_lock:
        _calls_since_sweep[0] += 1
        if _calls_since_sweep[0] >= _SWEEP_EVERY:
            # calls whose response never arrived (timeout, dead peer) must
            # not accumulate forever, nor mis-correlate after rid wrap
            _calls_since_sweep[0] = 0
            dead = [r for r, (_, exp) in _corr.items() if exp < now]
            for r in dead:
                del _corr[r]
        rid = _next_req_id[0]
        _next_req_id[0] = (rid + 1) & 0x7FFFFFFF or 1
        _corr[rid] = (cid, now + (ttl if ttl else _CORR_TTL))
        return rid


def _take_cid(response_to: int) -> Optional[int]:
    with _corr_lock:
        ent = _corr.pop(response_to, None)
        return ent[0] if ent is not None else None


# ---- protocol hooks ----------------------------------------------------

def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    head_bytes = source.fetch(HEAD_SIZE)
    if head_bytes is None:
        # not enough for a head: could still be mongo — but reject quickly
        # if the partial opcode can't match (the reference returns
        # TRY_OTHERS on bad opcode only once the head is complete)
        return ParseResult.not_enough_data()
    head = MongoHead.unpack(head_bytes)
    if head.op_code not in _KNOWN_OPCODES or \
            head.message_length < HEAD_SIZE or \
            head.message_length > _MAX_MESSAGE:
        return ParseResult.try_others()
    if len(source) < head.message_length:
        return ParseResult.not_enough_data()
    source.pop_front(HEAD_SIZE)
    body = source.cut(head.message_length - HEAD_SIZE).to_bytes()
    return ParseResult.ok(MongoMessage(head, body))


def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    if isinstance(request, MongoRequest):
        body = _pack_op_msg(request.doc)
        cntl._mongo_opcode = request.op_code
    elif isinstance(request, dict):
        body = _pack_op_msg(request)
        cntl._mongo_opcode = OP_MSG
    else:
        raise TypeError("mongo request must be MongoRequest or dict")
    return IOBuf(body)


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    ttl = (cntl.timeout_ms / 1000.0 + 30.0) if cntl.timeout_ms else None
    rid = _new_request_id(cid, ttl)
    body = payload.to_bytes()
    head = MongoHead(HEAD_SIZE + len(body), rid, 0,
                     getattr(cntl, "_mongo_opcode", OP_MSG))
    out = IOBuf()
    out.append(head.pack())
    out.append(body)
    return out


def process_response(msg: MongoMessage, socket) -> None:
    cid = _take_cid(msg.head.response_to)
    if cid is None:
        return                              # stale/unknown: drop
    rc, cntl = bthread_id.lock(cid)
    if rc != 0 or cntl is None:
        return
    resp = MongoResponse()
    resp.head = msg.head
    try:
        resp.doc = msg.doc
    except Exception as e:
        cntl.set_failed(errors.ERESPONSE, f"bad mongo reply: {e}")
        cntl.finish_parsed_response(cid)
        return
    cntl.response = resp
    cntl.finish_parsed_response(cid)


def process_request(msg: MongoMessage, socket, server) -> None:
    svc = None
    for s in getattr(server, "_services", {}).values():
        if isinstance(s, MongoService):
            svc = s
            break
    if svc is None:
        svc = getattr(server, "_mongo_service", None)
    err_doc = None
    reply: Optional[Dict[str, Any]] = None
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = socket.remote_side
    if svc is None:
        err_doc = {"ok": 0, "errmsg": "no MongoService registered",
                   "code": errors.ENOSERVICE}
    else:
        try:
            reply = svc.process(cntl, msg.doc)
        except Exception as e:
            err_doc = {"ok": 0, "errmsg": f"{type(e).__name__}: {e}",
                       "code": errors.EINTERNAL}
    out_doc = err_doc if err_doc is not None else (
        reply if reply is not None else {"ok": 1})
    body = _pack_op_msg(out_doc)
    head = MongoHead(HEAD_SIZE + len(body), 0, msg.head.request_id, OP_MSG)
    out = IOBuf()
    out.append(head.pack())
    out.append(body)
    socket.write(out)


PROTOCOL = Protocol(
    name="mongo",
    parse=parse,
    process_request=process_request,
    process_response=process_response,
    serialize_request=serialize_request,
    pack_request=pack_request,
)


def _register() -> None:
    from ..rpc.protocol import find_protocol
    if find_protocol("mongo") is None:
        register_protocol(PROTOCOL)


_register()
