"""public-pbrpc: nshead(version=1000) frames carrying one pb envelope.

Reference behavior: src/brpc/policy/public_pbrpc_protocol.cpp — the whole
nshead body is a single PublicRequest/PublicResponse message; the request
body list carries (service, method_id, id=correlation id, serialized
request), the response echoes the id, and errors ride responseHead.code.
Unlike nova, the correlation id IS on the wire, but frames still share the
nshead magic, so cutting stays with the shared `nshead` protocol and the
per-call context double-checks the echoed id.  Server side is an
NsheadPbServiceAdaptor registered like any nshead service.
"""
from __future__ import annotations

import re

from ..butil.iobuf import IOBuf
from ..bthread import id as bthread_id
from ..proto import legacy_meta_pb2 as legacy_pb
from ..rpc import errors
from ..rpc import compress as compress_mod
from ..rpc.controller import Controller
from ..rpc.protocol import (CONNECTION_TYPE_POOLED, CONNECTION_TYPE_SHORT,
                            Protocol, ParseResult, register_protocol,
                            find_protocol)
from .nshead import (NsheadCallCtx, NsheadHead, NsheadMessage,
                     NsheadPbServiceAdaptor)
from .legacy_pbrpc import _resp_meta_shim, _serialize_pb

NSHEAD_VERSION = 1000
PROVIDER = b"pbrpc"
_VERSIONISH = re.compile(r"[0-9.]*")


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    service, _, method_name = method_full_name.rpartition(".")
    env = legacy_pb.PublicRequest()
    env.requestHead.log_id = cntl.log_id
    if cntl.compress_type:
        env.requestHead.compress_type = cntl.compress_type
    body = env.requestBody.add()
    body.service = service
    body.method_id = getattr(cntl, "method_index", 0) or 0
    body.id = cid
    # carry the method name in `version` so name dispatch also works
    # (method_id stays authoritative for reference-shaped peers)
    body.version = method_name
    body.serialized_request = payload.to_bytes()
    data = env.SerializeToString()
    head = NsheadHead(version=NSHEAD_VERSION, provider=PROVIDER,
                      log_id=cntl.log_id, body_len=len(data))
    out = IOBuf()
    out.append(head.pack())
    out.append(data)
    return out


def _complete(msg: NsheadMessage, socket, ctx: NsheadCallCtx) -> None:
    rc, cntl = bthread_id.lock(ctx.cid)
    if rc != 0 or cntl is None:
        return
    cntl.remote_side = socket.remote_side
    env = legacy_pb.PublicResponse()
    try:
        env.ParseFromString(msg.body.to_bytes())
    except Exception as e:
        cntl.set_failed(errors.ERESPONSE, f"bad PublicResponse: {e}")
        cntl.finish_parsed_response(ctx.cid)
        return
    code = env.responseHead.code if env.HasField("responseHead") else 0
    text = env.responseHead.text if env.HasField("responseHead") else ""
    payload = IOBuf()
    if env.responseBody:
        rb = env.responseBody[0]
        if rb.id != ctx.cid:
            cntl.set_failed(errors.ERESPONSE,
                            f"response id {rb.id} != call id {ctx.cid}")
            cntl.finish_parsed_response(ctx.cid)
            return
        if rb.error and code == 0:
            code = rb.error
        payload.append(rb.serialized_response)
    cntl.handle_response(
        ctx.cid, _resp_meta_shim(code, text,
                                 env.responseHead.compress_type), payload)


def make_pipeline_ctx(cid: int, cntl: Controller) -> NsheadCallCtx:
    return NsheadCallCtx(cid, _complete, "public_pbrpc")


class PublicPbrpcServiceAdaptor(NsheadPbServiceAdaptor):
    """The server half: unwrap PublicRequest, dispatch by (service,
    method_id|method name), wrap the reply in PublicResponse."""

    def parse_nshead_meta(self, server, request, controller, meta) -> None:
        if request.head.version != NSHEAD_VERSION:
            controller.set_failed(errors.EREQUEST,
                                  f"bad nshead version {request.head.version}")
            return
        env = legacy_pb.PublicRequest()
        try:
            env.ParseFromString(request.body.to_bytes())
        except Exception as e:
            controller.set_failed(errors.EREQUEST, f"bad PublicRequest: {e}")
            return
        if not env.requestBody:
            controller.set_failed(errors.EREQUEST, "empty requestBody")
            return
        rb = env.requestBody[0]
        # record the envelope identity FIRST: failure responses must still
        # echo the caller's correlation id
        meta.correlation_id = rb.id
        meta.log_id = env.requestHead.log_id
        meta.compress_type = env.requestHead.compress_type
        svc = server._services.get(rb.service)
        if svc is None:
            controller.set_failed(errors.ENOSERVICE,
                                  f"no service {rb.service}")
            return
        # `version` is a version string for reference-shaped peers
        # ("1.0.0"); our client repurposes it to carry the method name.
        # A name-like version that matches no method is a typo'd method,
        # not an invitation to fall back to method_id 0.
        name_like = bool(rb.version) and not _VERSIONISH.fullmatch(rb.version)
        if name_like:
            full = f"{rb.service}.{rb.version}"
            if server.find_method(full) is None:
                controller.set_failed(errors.ENOMETHOD, f"no method {full}")
                return
            meta.full_method_name = full
        else:
            mds = list(svc.methods().values())
            if not (0 <= rb.method_id < len(mds)):
                controller.set_failed(errors.ENOMETHOD,
                                      f"bad method_id {rb.method_id}")
                return
            meta.full_method_name = mds[rb.method_id].full_name
        controller._public_serialized = rb.serialized_request

    def parse_request_from_iobuf(self, meta, request, controller,
                                 pb_req) -> None:
        data = getattr(controller, "_public_serialized", b"")
        try:
            if meta.compress_type:
                data = compress_mod.decompress(meta.compress_type, data)
            pb_req.ParseFromString(data)
        except Exception as e:
            controller.set_failed(errors.EREQUEST,
                                  f"fail to parse request: {e}")

    def serialize_response_to_iobuf(self, meta, controller, pb_res,
                                    response) -> None:
        env = legacy_pb.PublicResponse()
        env.responseHead.code = controller.error_code_
        if controller.error_text_:
            env.responseHead.text = controller.error_text_
        rb = env.responseBody.add()
        rb.id = meta.correlation_id
        if controller.failed():
            rb.error = controller.error_code_
        elif pb_res is not None:
            rb.serialized_response = pb_res.SerializeToString()
        response.head.version = NSHEAD_VERSION
        response.head.provider = PROVIDER
        response.body.append(env.SerializeToString())


PROTOCOL = Protocol(
    name="public_pbrpc",
    parse=lambda source, socket, read_eof, arg: ParseResult.try_others(),
    serialize_request=_serialize_pb,
    pack_request=pack_request,
    supported_connection_type=CONNECTION_TYPE_POOLED | CONNECTION_TYPE_SHORT,
    support_server=False,
    pipelined=True,
    make_pipeline_ctx=make_pipeline_ctx,
)


if find_protocol("public_pbrpc") is None:
    register_protocol(PROTOCOL)
