"""AMF0 codec — Action Message Format, the RTMP command/metadata encoding.

Reference: src/brpc/amf.{h,cpp} (AMFObject/AMFField at amf.h:40-170,
ReadAMFObject/WriteAMFObject).  The reference models AMF values with a
dedicated AMFObject class tree; here values map to native Python types
(float/bool/str/dict/list/None) plus three thin wrappers for markers that
have no native analogue: :class:`Undefined`, :class:`EcmaArray`,
:class:`AmfDate`.  Dicts preserve insertion order, matching the field
order the reference keeps in its vector-backed AMFObject.

Only AMF0 is implemented; AMF3 appears on the RTMP wire solely as the
0x11 command-message envelope whose body is AMF0 after a one-byte format
selector (handled in policy/rtmp.py), mirroring the reference's support
surface (rtmp_protocol.cpp treats AMF3 commands the same way).
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

# AMF0 type markers (amf.h:28-46 AMFMarker)
MARKER_NUMBER = 0x00
MARKER_BOOLEAN = 0x01
MARKER_STRING = 0x02
MARKER_OBJECT = 0x03
MARKER_MOVIECLIP = 0x04
MARKER_NULL = 0x05
MARKER_UNDEFINED = 0x06
MARKER_REFERENCE = 0x07
MARKER_ECMA_ARRAY = 0x08
MARKER_OBJECT_END = 0x09
MARKER_STRICT_ARRAY = 0x0A
MARKER_DATE = 0x0B
MARKER_LONG_STRING = 0x0C
MARKER_UNSUPPORTED = 0x0D
MARKER_XML_DOCUMENT = 0x0F
MARKER_TYPED_OBJECT = 0x10
MARKER_AVMPLUS_OBJECT = 0x11


class Undefined:
    """AMF0 'undefined' (distinct from null)."""
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "amf.UNDEFINED"


UNDEFINED = Undefined()


class EcmaArray(dict):
    """Associative array (marker 0x08): a dict that remembers it should be
    written with the ECMA-array marker rather than the object marker."""


class AmfDate:
    __slots__ = ("epoch_ms", "tz_minutes")

    def __init__(self, epoch_ms: float, tz_minutes: int = 0):
        self.epoch_ms = float(epoch_ms)
        self.tz_minutes = tz_minutes

    def __eq__(self, other):
        return (isinstance(other, AmfDate)
                and other.epoch_ms == self.epoch_ms
                and other.tz_minutes == self.tz_minutes)

    def __repr__(self):
        return f"AmfDate({self.epoch_ms}, tz={self.tz_minutes})"


class AmfError(ValueError):
    pass


# ---- encoding ----------------------------------------------------------

def _enc_utf8(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise AmfError("AMF0 short string over 65535 bytes")
    return struct.pack(">H", len(b)) + b


def _enc_props(out: List[bytes], d: Dict[str, Any]) -> None:
    for k, v in d.items():
        out.append(_enc_utf8(str(k)))
        _encode_value(out, v)
    out.append(b"\x00\x00" + bytes([MARKER_OBJECT_END]))


def _encode_value(out: List[bytes], v: Any) -> None:
    if v is None:
        out.append(bytes([MARKER_NULL]))
    elif v is UNDEFINED or isinstance(v, Undefined):
        out.append(bytes([MARKER_UNDEFINED]))
    elif isinstance(v, bool):
        out.append(bytes([MARKER_BOOLEAN, 1 if v else 0]))
    elif isinstance(v, (int, float)):
        out.append(bytes([MARKER_NUMBER]) + struct.pack(">d", float(v)))
    elif isinstance(v, str):
        b = v.encode("utf-8")
        if len(b) > 0xFFFF:
            out.append(bytes([MARKER_LONG_STRING])
                       + struct.pack(">I", len(b)) + b)
        else:
            out.append(bytes([MARKER_STRING]) + _enc_utf8(v))
    elif isinstance(v, AmfDate):
        out.append(bytes([MARKER_DATE])
                   + struct.pack(">dh", v.epoch_ms, v.tz_minutes))
    elif isinstance(v, EcmaArray):
        out.append(bytes([MARKER_ECMA_ARRAY]) + struct.pack(">I", len(v)))
        _enc_props(out, v)
    elif isinstance(v, dict):
        out.append(bytes([MARKER_OBJECT]))
        _enc_props(out, v)
    elif isinstance(v, (list, tuple)):
        out.append(bytes([MARKER_STRICT_ARRAY]) + struct.pack(">I", len(v)))
        for item in v:
            _encode_value(out, item)
    else:
        raise AmfError(f"cannot encode {type(v).__name__} as AMF0")


def encode(*values: Any) -> bytes:
    """Encode values back-to-back (an RTMP command body is a sequence of
    AMF0 values, not a single root)."""
    out: List[bytes] = []
    for v in values:
        _encode_value(out, v)
    return b"".join(out)


# ---- decoding ----------------------------------------------------------

def _dec_utf8(data: bytes, off: int) -> Tuple[str, int]:
    if off + 2 > len(data):
        raise AmfError("truncated string length")
    n = struct.unpack_from(">H", data, off)[0]
    off += 2
    if off + n > len(data):
        raise AmfError("truncated string body")
    return data[off:off + n].decode("utf-8", "replace"), off + n


def _dec_props(data: bytes, off: int, d: Dict[str, Any]) -> int:
    while True:
        key, off = _dec_utf8(data, off)
        if off >= len(data):
            raise AmfError("truncated object")
        if key == "" and data[off] == MARKER_OBJECT_END:
            return off + 1
        val, off = _decode_value(data, off)
        d[key] = val


def _decode_value(data: bytes, off: int) -> Tuple[Any, int]:
    if off >= len(data):
        raise AmfError("truncated value")
    marker = data[off]
    off += 1
    if marker == MARKER_NUMBER:
        if off + 8 > len(data):
            raise AmfError("truncated number")
        return struct.unpack_from(">d", data, off)[0], off + 8
    if marker == MARKER_BOOLEAN:
        if off >= len(data):
            raise AmfError("truncated boolean")
        return data[off] != 0, off + 1
    if marker == MARKER_STRING:
        return _dec_utf8(data, off)
    if marker in (MARKER_OBJECT, MARKER_TYPED_OBJECT):
        d: Dict[str, Any] = {}
        if marker == MARKER_TYPED_OBJECT:       # class name, then props
            _, off = _dec_utf8(data, off)
        off = _dec_props(data, off, d)
        return d, off
    if marker == MARKER_NULL:
        return None, off
    if marker in (MARKER_UNDEFINED, MARKER_UNSUPPORTED):
        return UNDEFINED, off
    if marker == MARKER_ECMA_ARRAY:
        if off + 4 > len(data):
            raise AmfError("truncated ecma array")
        off += 4                                # count is advisory
        arr = EcmaArray()
        off = _dec_props(data, off, arr)
        return arr, off
    if marker == MARKER_STRICT_ARRAY:
        if off + 4 > len(data):
            raise AmfError("truncated strict array")
        n = struct.unpack_from(">I", data, off)[0]
        off += 4
        items = []
        for _ in range(n):
            v, off = _decode_value(data, off)
            items.append(v)
        return items, off
    if marker == MARKER_DATE:
        if off + 10 > len(data):
            raise AmfError("truncated date")
        ms, tz = struct.unpack_from(">dh", data, off)
        return AmfDate(ms, tz), off + 10
    if marker in (MARKER_LONG_STRING, MARKER_XML_DOCUMENT):
        if off + 4 > len(data):
            raise AmfError("truncated long string")
        n = struct.unpack_from(">I", data, off)[0]
        off += 4
        if off + n > len(data):
            raise AmfError("truncated long string body")
        return data[off:off + n].decode("utf-8", "replace"), off + n
    raise AmfError(f"unsupported AMF0 marker 0x{marker:02x}")


def decode(data: bytes, offset: int = 0) -> Tuple[Any, int]:
    """Decode one value; returns (value, next_offset)."""
    return _decode_value(data, offset)


def decode_all(data: bytes) -> List[Any]:
    """Decode back-to-back values until the buffer is exhausted."""
    out = []
    off = 0
    while off < len(data):
        v, off = _decode_value(data, off)
        out.append(v)
    return out
