"""Naming services: cluster membership sources.

Reference: src/brpc/policy/*naming_service.cpp + details/
naming_service_thread.h (one shared polling thread per url).  Implemented
sources:

  * ``list://ep1,ep2,...``      static list (tags via ``ep weight tag``)
  * ``file://path``             one endpoint per line, re-read periodically;
                                supports ``endpoint weight tag`` columns and
                                the "N/M" partition tags PartitionChannel
                                parses (partition_channel.h:46-52)
  * ``dns://host:port``         resolve host each period (the reference's
                                http:// DomainNamingService)
  * ``mesh://``                 TPU-native: every device of the default ICI
                                mesh — topology discovery IS the naming
                                service on a pod
  * ``pod://<name>``            pod membership (ici/pod.py): every serving,
                                non-draining device of every up member —
                                join/leave/drain transitions move the pod
                                epoch and propagate within one watch poll
  * ``consul://host:port/name`` JSON HTTP discovery endpoint (consul-style
                                watch; plain GET per period)

A NamingServiceThread polls its source and pushes full server lists to
watchers (load balancers implement the watcher interface via
``reset_servers``).
"""
from __future__ import annotations

import json
import threading
import urllib.request
from typing import Callable, Dict, List, Optional

from ..butil.endpoint import EndPoint, parse_endpoint
from ..butil import logging as log
from ..butil import flags as _flags
from .load_balancers import ServerEntry

_flags.define_flag("ns_poll_interval_s", 1.0,
                   "naming service polling period")


class NamingService:
    def get_servers(self) -> List[ServerEntry]:
        raise NotImplementedError

    def supports_watch(self) -> bool:
        return False

    def watch(self) -> List[ServerEntry]:
        """One blocking watch round (sources with supports_watch()):
        returns when membership changed or the source's hold elapsed."""
        return self.get_servers()


def _parse_line(line: str) -> Optional[ServerEntry]:
    line = line.split("#", 1)[0].strip()
    if not line:
        return None
    parts = line.split()
    ep = parse_endpoint(parts[0])
    weight = int(parts[1]) if len(parts) > 1 and parts[1].isdigit() else 100
    tag = parts[-1] if len(parts) > 1 and not parts[-1].isdigit() else ""
    return ServerEntry(ep, weight, tag)


def _split_list(body: str) -> List[str]:
    """Split a list:// body on commas, but not inside ici mesh coords —
    ``list://ici://(0,1),ici://(0,2)`` is two entries, not four.  Spaces
    inside the parens are squeezed out so the whitespace-splitting
    _parse_line sees ``ici://(0,1)`` as one token."""
    out, depth, cur = [], 0, []
    for ch in body:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        elif ch.isspace() and depth > 0:
            continue
        else:
            cur.append(ch)
    out.append("".join(cur))
    return [x for x in out if x.strip()]


def is_naming_url(target: str) -> bool:
    """True when ``target`` is a naming-service url (mesh://, pod://,
    list://, file://, http://, …) rather than a direct endpoint scheme —
    the ONE predicate Channel.init, rpc_press, and the examples share,
    so a new direct-endpoint scheme is added in exactly one place."""
    return "://" in target and not target.startswith(
        ("mem://", "ici://", "tcp://"))


def resolve_servers(target: str) -> List[str]:
    """One endpoint url per resolved server — the ONE resolver the CLI
    tools (rpc_press, rpc_view) share.  A naming url resolves through
    its naming service; a comma-separated list is split (ici mesh
    coords' parens respected); a single endpoint passes through.
    Raises ValueError on empty resolution — a typo'd pod name must not
    silently target nothing."""
    # a COMMA LIST whose first entry is a bare host:port but whose later
    # entries carry schemes ("127.0.0.1:80,mem://x") contains "://" and
    # would satisfy is_naming_url — but a real naming url's scheme part
    # (before the first "://") can never contain a comma
    if is_naming_url(target) and "," not in target.split("://", 1)[0]:
        entries = create_naming_service(target).get_servers()
        out = [str(e.endpoint) for e in entries]
        if not out:
            raise ValueError(f"{target} resolved to no servers")
        return out
    if "," in target:
        out = _split_list(target)
        if not out:
            raise ValueError(f"empty server list {target!r}")
        return out
    return [target]


class ListNamingService(NamingService):
    def __init__(self, body: str):
        self._entries = []
        for item in _split_list(body):
            e = _parse_line(item.replace(":tag=", " "))
            if e is not None:
                self._entries.append(e)

    def get_servers(self) -> List[ServerEntry]:
        return list(self._entries)


class FileNamingService(NamingService):
    def __init__(self, path: str):
        self.path = path

    def get_servers(self) -> List[ServerEntry]:
        out = []
        with open(self.path) as f:
            for line in f:
                e = _parse_line(line)
                if e is not None:
                    out.append(e)
        return out


class DnsNamingService(NamingService):
    def __init__(self, hostport: str):
        host, _, port = hostport.rpartition(":")
        self.host = host
        self.port = int(port)

    def get_servers(self) -> List[ServerEntry]:
        import socket
        infos = socket.getaddrinfo(self.host, self.port,
                                   socket.AF_INET, socket.SOCK_STREAM)
        eps = sorted({info[4][0] for info in infos})
        return [ServerEntry(EndPoint(scheme="tcp", host=ip, port=self.port))
                for ip in eps]


class MeshNamingService(NamingService):
    """Device mesh topology as membership: ici://0..n-1, with the device
    kind as tag.  On a real pod the mesh shape comes from the runtime, so
    membership tracks the hardware — no registry to operate."""

    def get_servers(self) -> List[ServerEntry]:
        from ..ici.mesh import IciMesh
        from ..rpc import lameduck
        mesh = IciMesh.default()
        out = []
        for i in range(mesh.size):
            ep = mesh.endpoint(i)
            # lame-duck: a draining member (local server in drain, or a
            # peer that sent GOODBYE) is pulled from topology-derived
            # membership until its restart revives it
            if lameduck.is_draining(ep):
                continue
            out.append(ServerEntry(ep, 100, tag=str(mesh.device(i))))
        return out


class PodNamingService(NamingService):
    """``pod://<name>``: the pod membership table as a server list —
    every serving, non-draining device of every up member (ici/pod.py).
    Membership is the record; liveness stays with the health checker and
    circuit breakers (the reference's naming+LB division of labor).  A
    process that has not joined the pod gets an empty list (and a
    warning once) rather than an error — membership may begin later."""

    def __init__(self, name: str):
        self.pod_name = name or "default"
        self._warned = False

    def get_servers(self) -> List[ServerEntry]:
        from ..ici.pod import Pod
        pod = Pod.current()
        if pod is None or pod.name != self.pod_name:
            if not self._warned:
                self._warned = True
                log.warning("pod://%s: this process has not joined the "
                            "pod; membership is empty until Pod.join",
                            self.pod_name)
            return []
        from ..rpc import lameduck
        out = []
        for ep, pid in pod.serving_endpoints():
            if lameduck.is_draining(ep):
                continue            # GOODBYE beat the membership record
            out.append(ServerEntry(ep, 100, tag=f"pid={pid}"))
        return out


class ConsulNamingService(NamingService):
    """Consul health API with the BLOCKING long-poll watch (reference
    policy/consul_naming_service.cpp:99-114): the first GET primes the
    membership index from the ``X-Consul-Index`` response header, and
    every subsequent round long-polls
    ``.../v1/health/service/<name>?index=<last>&wait=60s`` — the server
    holds the request open until membership moves past <last> (or the
    wait elapses), so changes propagate in one round trip instead of one
    polling period.  Also accepts a plain JSON list of "host:port"
    strings for generic HTTP discovery (no index header → degrades to
    plain periodic GETs through the same code path)."""

    WAIT = "60s"            # consul-side hold; client timeout adds slack

    def __init__(self, rest: str):
        hostport, _, name = rest.partition("/")
        self.url = f"http://{hostport}/v1/health/service/{name}"
        self.last_index: Optional[str] = None

    def supports_watch(self) -> bool:
        return True

    def _fetch(self, url: str, timeout: float):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return (r.headers.get("X-Consul-Index"),
                    json.loads(r.read().decode()))

    @staticmethod
    def parse_health_response(data) -> List[ServerEntry]:
        out = []
        for item in data:
            if isinstance(item, str):
                out.append(ServerEntry(parse_endpoint(item)))
            else:
                svc = item.get("Service", {})
                out.append(ServerEntry(
                    EndPoint(scheme="tcp", host=svc.get("Address", ""),
                             port=int(svc.get("Port", 0))),
                    tag=",".join(svc.get("Tags") or [])))
        return out

    def get_servers(self) -> List[ServerEntry]:
        idx, data = self._fetch(self.url, timeout=5)
        if idx:
            self.last_index = idx
        return self.parse_health_response(data)

    def watch(self) -> List[ServerEntry]:
        """One blocking watch round; returns the (possibly unchanged)
        membership when the server releases the poll."""
        if self.last_index is None:
            return self.get_servers()        # prime the index first
        url = f"{self.url}?index={self.last_index}&wait={self.WAIT}"
        idx, data = self._fetch(url, timeout=75.0)
        if idx:
            self.last_index = idx
        return self.parse_health_response(data)


class RemoteFileNamingService(NamingService):
    """remotefile://<url-without-scheme>: fetch a server list over HTTP,
    one "host:port [tag]" per line (policy/remote_file_naming_service.cpp)."""

    def __init__(self, rest: str):
        self.url = rest if rest.startswith(("http://", "https://")) \
            else f"http://{rest}"

    def get_servers(self) -> List[ServerEntry]:
        with urllib.request.urlopen(self.url, timeout=5) as r:
            body = r.read().decode()
        out = []
        for line in body.splitlines():
            e = _parse_line(line)
            if e is not None:
                out.append(e)
        return out


class NacosNamingService(NamingService):
    """nacos://host:port/serviceName[?namespaceId=..&groupName=..]:
    Nacos open API GET /nacos/v1/ns/instance/list
    (policy/nacos_naming_service.cpp; JSON {"hosts": [{"ip", "port",
    "weight", "healthy", "enabled"}]}).  Weights scale the reference's
    default 100 so weighted LBs keep working."""

    def __init__(self, rest: str):
        hostport, _, svc = rest.partition("/")
        name, _, query = svc.partition("?")
        q = f"serviceName={name}" + (f"&{query}" if query else "")
        self.url = f"http://{hostport}/nacos/v1/ns/instance/list?{q}"

    def get_servers(self) -> List[ServerEntry]:
        with urllib.request.urlopen(self.url, timeout=5) as r:
            data = json.loads(r.read().decode())
        out = []
        for h in data.get("hosts", []):
            if not h.get("healthy", True) or not h.get("enabled", True):
                continue
            out.append(ServerEntry(
                EndPoint(scheme="tcp", host=str(h.get("ip", "")),
                         port=int(h.get("port", 0))),
                weight=int(float(h.get("weight", 1.0)) * 100),
                tag=str(h.get("clusterName", ""))))
        return out


class DiscoveryNamingService(NamingService):
    """discovery://host:port/appid[?env=..&status=1]: Bilibili discovery
    GET /discovery/fetchs (policy/discovery_naming_service.cpp; JSON
    {"data": {appid: {"instances": [{"addrs": ["scheme://ip:port"],
    "status": 1}]}}})."""

    def __init__(self, rest: str):
        hostport, _, app = rest.partition("/")
        self.appid, _, query = app.partition("?")
        q = f"appid={self.appid}" + (f"&{query}" if query else
                                     "&env=prod&status=1")
        self.url = f"http://{hostport}/discovery/fetchs?{q}"

    def get_servers(self) -> List[ServerEntry]:
        with urllib.request.urlopen(self.url, timeout=5) as r:
            data = json.loads(r.read().decode())
        out = []
        app = data.get("data", {}).get(self.appid, {})
        for inst in app.get("instances", []):
            if inst.get("status", 1) != 1:
                continue
            for addr in inst.get("addrs", []):
                _, _, hp = addr.partition("://")
                host, _, port = hp.rpartition(":")
                if host and port.isdigit():
                    out.append(ServerEntry(
                        EndPoint(scheme="tcp", host=host, port=int(port)),
                        tag=str(inst.get("zone", ""))))
        return out


def create_naming_service(url: str) -> NamingService:
    scheme, _, rest = url.partition("://")
    if scheme == "list":
        return ListNamingService(rest)
    if scheme == "file":
        return FileNamingService(rest)
    if scheme in ("dns", "http", "https"):
        return DnsNamingService(rest)
    if scheme == "mesh":
        return MeshNamingService()
    if scheme == "pod":
        return PodNamingService(rest)
    if scheme == "consul":
        return ConsulNamingService(rest)
    if scheme == "remotefile":
        return RemoteFileNamingService(rest)
    if scheme == "nacos":
        return NacosNamingService(rest)
    if scheme == "discovery":
        return DiscoveryNamingService(rest)
    raise ValueError(f"unknown naming service scheme {scheme!r}")


class NamingServiceThread:
    """Shared per-url poller (details/naming_service_thread.h:58)."""

    def __init__(self, url: str, filter_fn: Optional[Callable] = None):
        self.url = url
        self.ns = create_naming_service(url)
        self.filter_fn = filter_fn
        self._watchers: List = []
        self._lock = threading.Lock()
        self._last: List[ServerEntry] = []
        self._have_last = False
        self._stop = threading.Event()
        # fablint: thread-quiesced(stop() sets _stop; the watch/poll loop checks it every iteration and exits promptly)
        self._thread = threading.Thread(target=self._run,
                                        name=f"ns:{url[:24]}", daemon=True)
        self._poll_once()
        self._thread.start()

    def add_watcher(self, watcher) -> None:
        """watcher has reset_servers(List[ServerEntry])."""
        with self._lock:
            self._watchers.append(watcher)
            if self._have_last:
                watcher.reset_servers(self._last)

    def remove_watcher(self, watcher) -> None:
        with self._lock:
            try:
                self._watchers.remove(watcher)
            except ValueError:
                pass

    def servers(self) -> List[ServerEntry]:
        with self._lock:
            return list(self._last)

    def _poll_once(self) -> None:
        try:
            entries = self.ns.get_servers()
        except Exception as e:
            log.log_every_n(log.WARNING, 60, "naming %s failed: %s",
                            self.url, e)
            return
        self._publish(entries)

    def _publish(self, entries: List[ServerEntry]) -> None:
        if self.filter_fn is not None:
            entries = [e for e in entries if self.filter_fn(e)]
        with self._lock:
            changed = (not self._have_last
                       or [(str(e.endpoint), e.weight, e.tag) for e in entries]
                       != [(str(e.endpoint), e.weight, e.tag) for e in self._last])
            self._last = entries
            self._have_last = True
            watchers = list(self._watchers)
        if changed:
            for w in watchers:
                try:
                    w.reset_servers(entries)
                except Exception:
                    pass

    def _run(self) -> None:
        if self.ns.supports_watch():
            # blocking watch loop: each round holds a long poll at the
            # source (consul index=/wait=) and publishes the moment it
            # releases — membership changes propagate in one round trip,
            # not one polling period.  Errors degrade to the polling
            # cadence so a down registry isn't hammered.
            while not self._stop.is_set():
                try:
                    entries = self.ns.watch()
                except Exception as e:
                    log.log_every_n(log.WARNING, 60,
                                    "naming watch %s failed: %s",
                                    self.url, e)
                    if self._stop.wait(_flags.get_flag("ns_poll_interval_s")):
                        return
                    continue
                self._publish(entries)
                if getattr(self.ns, "last_index", "armed") is None:
                    # the source answered without a blocking index (a
                    # plain-JSON discovery endpoint): degrade to the
                    # polling cadence instead of hot-looping GETs
                    if self._stop.wait(
                            _flags.get_flag("ns_poll_interval_s")):
                        return
            return
        while not self._stop.wait(_flags.get_flag("ns_poll_interval_s")):
            self._poll_once()

    def stop(self) -> None:
        self._stop.set()


_threads: Dict[str, NamingServiceThread] = {}
_threads_lock = threading.Lock()


def get_naming_service_thread(url: str) -> NamingServiceThread:
    with _threads_lock:
        t = _threads.get(url)
        if t is None:
            t = NamingServiceThread(url)
            _threads[url] = t
        return t
