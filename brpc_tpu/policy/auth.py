"""Authentication (reference: src/brpc/authenticator.h + policy/ giano/
couchbase/esp/redis authenticators).

An Authenticator generates a credential on the client (attached to the
first request meta) and verifies it on the server; verification failure
fails the RPC with ERPCAUTH before user code runs (tpu_std.process_request).
"""
from __future__ import annotations

import hashlib
import hmac
import time


class Authenticator:
    def generate_credential(self, cntl) -> str:
        raise NotImplementedError

    def verify(self, token: str, socket) -> bool:
        """Called by the server protocol; returning False → ERPCAUTH."""
        raise NotImplementedError


class TokenAuthenticator(Authenticator):
    """Shared-secret bearer token."""

    def __init__(self, token: str):
        self._token = token

    def generate_credential(self, cntl) -> str:
        return self._token

    def verify(self, token: str, socket) -> bool:
        return hmac.compare_digest(token, self._token)


class HmacAuthenticator(Authenticator):
    """Time-windowed HMAC(secret, window) credential — replay-bounded
    (the giano-style signed-credential shape, reimplemented simply)."""

    def __init__(self, key: str, window_s: int = 60):
        self._key = key.encode()
        self._window_s = window_s

    def _sig(self, window: int) -> str:
        return hmac.new(self._key, str(window).encode(),
                        hashlib.sha256).hexdigest()

    def generate_credential(self, cntl) -> str:
        window = int(time.time()) // self._window_s
        return f"{window}:{self._sig(window)}"

    def verify(self, token: str, socket) -> bool:
        try:
            window_str, sig = token.split(":", 1)
            window = int(window_str)
        except ValueError:
            return False
        now_window = int(time.time()) // self._window_s
        if abs(window - now_window) > 1:
            return False                  # expired credential
        return hmac.compare_digest(sig, self._sig(window))


class RedisAuthenticator(Authenticator):
    """Redis AUTH (policy/redis_authenticator.{h,cpp}): the credential is
    the password (or "user password" for Redis 6 ACL); the redis protocol
    prepends an AUTH command on each connection's first call and consumes
    its reply (pack_request/process_response in policy/redis.py)."""

    def __init__(self, password: str, user: str = ""):
        # NUL-joined so passwords containing spaces survive the arg split
        # in policy/redis.py pack_request
        self._cred = f"{user}\x00{password}" if user else password

    def generate_credential(self, cntl) -> str:
        return self._cred

    def verify(self, token: str, socket) -> bool:
        return hmac.compare_digest(token, self._cred)


class CouchbaseAuthenticator(Authenticator):
    """SASL PLAIN over the memcache binary protocol
    (policy/couchbase_authenticator.{h,cpp}): credential "user:password";
    the memcache protocol sends OP_SASL_AUTH first on each connection."""

    def __init__(self, user: str, password: str):
        self._cred = f"{user}:{password}"

    def generate_credential(self, cntl) -> str:
        return self._cred

    def verify(self, token: str, socket) -> bool:
        return hmac.compare_digest(token, self._cred)


class EspAuthenticator(Authenticator):
    """ESP magic-number credential (policy/esp_authenticator.cpp:7-15:
    6-byte magic + 2-byte local port); servers accept anything, matching
    the reference's no-op VerifyCredential."""

    _MAGIC = b"\x00ESP\x01\x02"

    def generate_credential(self, cntl) -> str:
        return (self._MAGIC + b"\x00\x00").decode("latin-1")

    def verify(self, token: str, socket) -> bool:
        return True
