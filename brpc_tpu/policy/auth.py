"""Authentication (reference: src/brpc/authenticator.h + policy/ giano/
couchbase/esp/redis authenticators).

An Authenticator generates a credential on the client (attached to the
first request meta) and verifies it on the server; verification failure
fails the RPC with ERPCAUTH before user code runs (tpu_std.process_request).
"""
from __future__ import annotations

import hashlib
import hmac
import time
from typing import Any, Optional


class Authenticator:
    def generate_credential(self, cntl) -> str:
        raise NotImplementedError

    def verify(self, token: str, socket) -> bool:
        """Called by the server protocol; returning False → ERPCAUTH."""
        raise NotImplementedError


class TokenAuthenticator(Authenticator):
    """Shared-secret bearer token."""

    def __init__(self, token: str):
        self._token = token

    def generate_credential(self, cntl) -> str:
        return self._token

    def verify(self, token: str, socket) -> bool:
        return hmac.compare_digest(token, self._token)


class HmacAuthenticator(Authenticator):
    """Time-windowed HMAC(secret, window) credential — replay-bounded
    (the giano-style signed-credential shape, reimplemented simply)."""

    def __init__(self, key: str, window_s: int = 60):
        self._key = key.encode()
        self._window_s = window_s

    def _sig(self, window: int) -> str:
        return hmac.new(self._key, str(window).encode(),
                        hashlib.sha256).hexdigest()

    def generate_credential(self, cntl) -> str:
        window = int(time.time()) // self._window_s
        return f"{window}:{self._sig(window)}"

    def verify(self, token: str, socket) -> bool:
        try:
            window_str, sig = token.split(":", 1)
            window = int(window_str)
        except ValueError:
            return False
        now_window = int(time.time()) // self._window_s
        if abs(window - now_window) > 1:
            return False                  # expired credential
        return hmac.compare_digest(sig, self._sig(window))
