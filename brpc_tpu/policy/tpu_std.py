"""tpu_std: the canonical framed protocol (the baidu_std analogue).

Reference behavior: src/brpc/policy/baidu_rpc_protocol.cpp — 12-byte header
("PRPC", body_size, meta_size), protobuf RpcMeta, payload, then attachment;
server path ProcessRpcRequest (:312), response path SendRpcResponse (:139),
client path ProcessRpcResponse (:557).  This implementation keeps the frame
shape (magic "TRPC" + u32 meta_size + u32 body_size) with our own RpcMeta
schema (brpc_tpu/proto/rpc_meta.proto) and adds nothing CUDA/torch-ish: the
same frames travel over mem://, tcp://, and the ici:// device fabric.
"""
from __future__ import annotations

import time
from typing import Any

from .. import bvar
from ..butil.iobuf import IOBuf
from ..butil import flags as _flags
from ..butil import logging as log
from ..bthread import id as bthread_id
from ..proto import rpc_meta_pb2 as meta_pb
from ..rpc import errors
from ..rpc import rpc_dump
from ..rpc.controller import Controller, server_controller_pool
from ..rpc.span import start_server_span, end_server_span
from ..rpc.protocol import Protocol, ParseResult, register_protocol
from ..rpc import compress as compress_mod

MAGIC = b"TRPC"
HEADER_SIZE = 12

# ---- server-side latency decomposition (ROADMAP item 1's measurement
# substrate): where does a request's time go on the tpu_std/ici server
# path?  Five stages, each a LatencyRecorder (p50..p9999 exposed under
# tpu_std_server_<stage>_*) plus an rpcz annotation on the request's
# span:
#   queue   — frame cut on the read loop → process_request entry
#             (messenger dispatch + usercode-pool queue wait)
#   parse   — request payload decompress + ParseFromString
#   handler — md.invoke → done() (user code)
#   encode  — response meta/payload serialization + frame pack
#   write   — socket.write (transport enqueue + inline drain)
# Default "sampled" decomposes only rpcz-sampled requests, as SPAN
# ANNOTATIONS only — a LatencyRecorder `<<` measures ~4 µs and five
# stages would burn ~27 µs per request, blowing the ≤10% tracing
# budget on the 46 µs Python-handler path.  "on" additionally feeds
# the five tpu_std_server_<stage> recorders on EVERY request (the
# /vars-distribution mode for dedicated measurement runs); "off"
# disables everything.
_flags.define_flag("tpu_std_stage_metrics", "sampled",
                   "per-stage server latency decomposition: 'sampled' "
                   "(annotations on rpcz-sampled spans), 'on' (every "
                   "request + bvar recorders), 'off'")

_STAGES = ("queue", "parse", "handler", "encode", "write")
_stage_recorders = {s: bvar.LatencyRecorder(f"tpu_std_server_{s}")
                    for s in _STAGES}
# the Flag OBJECT, read as one attribute load per request instead of a
# registry-dict lookup per stage check (hot path)
_stage_flag = _flags.flag_object("tpu_std_stage_metrics")


def _stages_active(cntl: Controller) -> bool:
    mode = _stage_flag.value
    if mode == "on":
        return True
    if mode == "off":
        return False
    return cntl.span is not None


def _record_stage(stage: str, us: int, span) -> None:
    if _stage_flag.value == "on":
        _stage_recorders[stage] << us
    if span is not None:
        span.annotate(f"{stage}_us={us}")


def stage_p50s_us() -> dict:
    """Per-stage p50s from the tpu_std_server_* recorders (µs) — the
    BENCH `extra` decomposition (only meaningful after a run with
    tpu_std_stage_metrics=on).  Reads the lifetime reservoir, not the
    10s window: a short measurement pass finishes before the window
    sampler's first tick."""
    return {s: _stage_recorders[s]._percentile.get_value().get_number(0.5)
            for s in _STAGES}


class StdMessage:
    """A cut but not yet parsed frame.  ``recv_ns`` stamps the cut on
    the read loop — the queue-wait stage's start."""
    __slots__ = ("meta", "body", "recv_ns")

    def __init__(self, meta: meta_pb.RpcMeta, body: IOBuf):
        self.meta = meta
        self.body = body
        self.recv_ns = 0


# ---- frame codec ------------------------------------------------------

def pack_frame(meta: meta_pb.RpcMeta, payload: IOBuf) -> IOBuf:
    meta_bytes = meta.SerializeToString()
    out = IOBuf()
    out.append(MAGIC + len(meta_bytes).to_bytes(4, "big")
               + len(payload).to_bytes(4, "big") + meta_bytes)
    out.append(payload)            # zero-copy ref share (device blocks ride)
    return out


def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    header = source.fetch(HEADER_SIZE)
    if header is None:
        prefix = source.fetch(min(len(source), 4)) or b""
        if MAGIC.startswith(prefix):
            return ParseResult.not_enough_data()
        return ParseResult.try_others()
    if header[:4] != MAGIC:
        return ParseResult.try_others()
    meta_size = int.from_bytes(header[4:8], "big")
    body_size = int.from_bytes(header[8:12], "big")
    if meta_size > (1 << 26) or body_size > (1 << 31):
        return ParseResult.parse_error("absurd frame sizes")
    total = HEADER_SIZE + meta_size + body_size
    if len(source) < total:
        return ParseResult.not_enough_data()
    source.pop_front(HEADER_SIZE)
    meta_buf = source.cut(meta_size)
    body = source.cut(body_size)
    meta = meta_pb.RpcMeta()
    try:
        meta.ParseFromString(meta_buf.to_bytes())
    except Exception as e:
        return ParseResult.parse_error(f"bad meta: {e}")
    msg = StdMessage(meta, body)
    msg.recv_ns = time.monotonic_ns()
    return ParseResult.ok(msg)


# ---- client side ------------------------------------------------------

def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    buf = IOBuf()
    if request is None:
        return buf
    if hasattr(request, "SerializeToString"):
        data = request.SerializeToString()
    elif isinstance(request, (bytes, bytearray)):
        data = bytes(request)
    else:
        raise TypeError(f"cannot serialize {type(request)}")
    if cntl.compress_type:
        data = compress_mod.compress(cntl.compress_type, data)
    buf.append(data)
    return buf


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    meta = meta_pb.RpcMeta()
    service, _, method_name = method_full_name.rpartition(".")
    meta.request.service_name = service
    meta.request.method_name = method_name
    if cntl.stream_creator is not None:     # stream handshake rides the RPC
        meta.stream_settings.stream_id = cntl.stream_creator.sid
        meta.stream_settings.frame_type = 4
        meta.stream_settings.need_feedback = True
    meta.request.log_id = cntl.log_id
    meta.correlation_id = cid
    meta.compress_type = cntl.compress_type
    if cntl.timeout_ms:
        meta.request.timeout_ms = cntl.timeout_ms
        # deadline budget REMAINING at send time (shrinks at each hop):
        # total budget minus what this caller already spent — a retry
        # issued late in the budget tells the server how little is left,
        # and the server sheds it before any work once it hits zero
        elapsed_ms = (time.monotonic_ns() // 1000
                      - cntl._start_us) / 1000.0 if cntl._start_us else 0.0
        meta.request.deadline_left_ms = max(
            int(cntl.timeout_ms - elapsed_ms), 1)
    if cntl.auth_token:
        meta.request.auth_token = cntl.auth_token
    if cntl.priority is not None:
        # offset-encoded: 0 on the wire = unset (server default band)
        meta.request.priority = cntl.priority + 1
    if cntl.tenant:
        meta.request.tenant = cntl.tenant
    if cntl.span is not None:
        meta.request.trace_id = cntl.span.trace_id
        meta.request.span_id = cntl.span.span_id
        meta.request.parent_span_id = cntl.span.parent_span_id
    body = IOBuf()
    body.append(payload)
    att_size = len(cntl.request_attachment)
    if att_size:
        meta.attachment_size = att_size
        body.append(cntl.request_attachment)
    return pack_frame(meta, body)


def process_inline(msg: StdMessage, socket) -> bool:
    """Reader-order consumption of stream frames (data/feedback/close):
    their relative order is the stream's byte order, so they must never go
    through the concurrent per-message dispatch."""
    meta = msg.meta
    if (meta.correlation_id == 0 and not meta.request.service_name
            and meta.HasField("stream_settings")):
        from ..rpc.stream import on_stream_frame
        on_stream_frame(meta, msg.body, socket)
        return True
    return False


def process_response(msg: StdMessage, socket) -> None:
    """ProcessRpcResponse: lock the correlation id; stale versions fail to
    lock and the response is dropped (the retry-race resolution)."""
    if msg.meta.correlation_id == 0 and msg.meta.HasField("stream_settings"):
        from ..rpc.stream import on_stream_frame
        on_stream_frame(msg.meta, msg.body, socket)
        return
    cid = msg.meta.correlation_id
    rc, cntl = bthread_id.lock(cid)
    if rc != 0 or cntl is None:
        return                      # stale/duplicate/cancelled — ignore
    cntl.remote_side = socket.remote_side
    if (msg.meta.HasField("stream_settings")
            and cntl.stream_creator is not None):
        # handshake completion: server accepted our stream
        cntl.stream_creator.mark_connected(
            msg.meta.stream_settings.remote_stream_id, socket)
    cntl.handle_response(cid, msg.meta, msg.body)


# ---- server side ------------------------------------------------------

def process_request(msg: StdMessage, socket, server) -> None:
    """ProcessRpcRequest (baidu_rpc_protocol.cpp:312): find method, check
    limits, run user code in this tasklet, respond via socket write.
    The per-request Controller comes from the server-side pool
    (controller.server_controller_pool) and is recycled once the
    response is written — the reference keeps this path allocation-free
    the same way."""
    meta = msg.meta
    if not meta.request.service_name and meta.HasField("stream_settings"):
        from ..rpc.stream import on_stream_frame
        on_stream_frame(meta, msg.body, socket)
        return
    req_meta = meta.request
    full_name = f"{req_meta.service_name}.{req_meta.method_name}"
    cid = meta.correlation_id
    start_us = time.monotonic_ns() // 1000
    if rpc_dump.dump_enabled():
        rpc_dump.maybe_dump_request(pack_frame(meta, msg.body))

    cntl = server_controller_pool.acquire()  # fablint: custody-moved(request-lifecycle) the shim rides the request; _maybe_recycle releases it back to the pool when the response (or failure path) completes
    cntl.server = server
    cntl.log_id = req_meta.log_id
    cntl.remote_side = socket.remote_side
    if req_meta.auth_token:
        cntl.auth_token = req_meta.auth_token
    if meta.compress_type:
        cntl.compress_type = meta.compress_type
    if req_meta.timeout_ms:
        cntl.method_deadline = time.monotonic() + req_meta.timeout_ms / 1000.0
    # admission-control propagation (offset-decoded; handlers may read)
    if req_meta.priority:
        cntl.priority = req_meta.priority - 1
    if req_meta.tenant:
        cntl.tenant = req_meta.tenant
    if req_meta.deadline_left_ms:
        cntl.deadline_left_ms = req_meta.deadline_left_ms

    start_server_span(cntl, full_name, req_meta.trace_id,
                      req_meta.span_id)
    stages = _stages_active(cntl)
    if stages and msg.recv_ns:
        _record_stage("queue",
                      (time.monotonic_ns() - msg.recv_ns) // 1000,
                      cntl.span)
    md = server.find_method(full_name)
    status = server.method_status(full_name) if md is not None else None
    server_counted = [False]
    handler_t0 = [0]

    def send_response(resp: Any = None) -> None:
        t_enc0 = time.monotonic_ns() if stages else 0
        if stages and handler_t0[0]:
            _record_stage("handler", (t_enc0 - handler_t0[0]) // 1000,
                          cntl.span)
        rmeta = meta_pb.RpcMeta()
        rmeta.correlation_id = cid
        rmeta.response.error_code = cntl.error_code_
        rmeta.response.error_text = cntl.error_text_
        if cntl.retry_after_ms:
            # admission shed hint: how long the client should back off
            rmeta.response.retry_after_ms = cntl.retry_after_ms
        if cntl.accepted_stream_id and not cntl.failed():
            # complete the stream handshake: echo ids both ways
            from ..rpc.stream import find_stream
            srv_stream = find_stream(cntl.accepted_stream_id)
            client_sid = meta.stream_settings.stream_id
            if srv_stream is not None:
                rmeta.stream_settings.stream_id = client_sid
                rmeta.stream_settings.remote_stream_id = cntl.accepted_stream_id
                srv_stream.mark_connected(client_sid, socket)
        payload = IOBuf()
        if resp is not None and not cntl.failed():
            data = resp.SerializeToString() if hasattr(resp, "SerializeToString") \
                else bytes(resp)
            if meta.compress_type:
                data = compress_mod.compress(meta.compress_type, data)
                rmeta.compress_type = meta.compress_type
            payload.append(data)
        resp_att = cntl._peek_response_attachment()
        att_size = len(resp_att) if resp_att is not None else 0
        if att_size:
            rmeta.attachment_size = att_size
            payload.append(resp_att)
        frame = pack_frame(rmeta, payload)
        t_wr0 = time.monotonic_ns() if stages else 0
        if stages:
            _record_stage("encode", (t_wr0 - t_enc0) // 1000, cntl.span)
        socket.write(frame)
        if stages:
            _record_stage("write",
                          (time.monotonic_ns() - t_wr0) // 1000,
                          cntl.span)
        if cntl.span is not None:
            end_server_span(cntl)
        if status is not None:
            status.on_responded(cntl.error_code_,
                                time.monotonic_ns() // 1000 - start_us)
        if server_counted[0]:
            server.on_request_out()

    if server.is_draining():
        # lame-duck: a draining server rejects NEW requests with
        # retryable ELOGOFF so clients fail over to another replica
        # instantly; work admitted before the drain flipped keeps running
        # inside the grace window (stream frames never reach here — they
        # ride process_inline)
        cntl.set_failed(errors.ELOGOFF, "server is draining (lame duck)")
        status = None       # don't on_responded a rejected request
        send_response()
        cntl._maybe_recycle()
        return

    def _parse_and_invoke() -> None:
        # parse request payload (gates held; send_response accounts)
        t_parse0 = time.monotonic_ns() if stages else 0
        try:
            body = msg.body
            if meta.attachment_size:
                keep = len(body) - meta.attachment_size
                payload_part = body.cut(keep)
                body.cutn(cntl.request_attachment, meta.attachment_size)
                body = payload_part
            data = body.to_bytes()
            if meta.compress_type:
                data = compress_mod.decompress(meta.compress_type, data)
            request = md.request_cls()
            request.ParseFromString(data)
        except Exception as e:
            cntl.set_failed(errors.EREQUEST, f"fail to parse request: {e}")
            send_response()
            cntl._maybe_recycle()
            return
        if stages:
            _record_stage("parse",
                          (time.monotonic_ns() - t_parse0) // 1000,
                          cntl.span)

        response = md.response_cls()
        done_called = [False]
        handler_t0[0] = time.monotonic_ns() if stages else 0

        def done() -> None:
            if done_called[0]:
                return
            done_called[0] = True
            send_response(response)

        cntl.set_server_done(done)
        try:
            md.invoke(cntl, request, response, done)
        except Exception as e:   # uncaught user exception → EINTERNAL
            log.error("method %s raised: %s", full_name, e, exc_info=True)
            if not done_called[0]:
                cntl.set_failed(errors.EINTERNAL, f"{type(e).__name__}: {e}")
                done()
                cntl._release_session_data()
                cntl._maybe_recycle()

    adm = server.admission
    if adm is None:
        # historical reject-at-gate path (no admission layer)
        if not server.on_request_in():
            cntl.set_failed(errors.ELIMIT, "server max_concurrency reached")
            status = None   # rejected before on_requested: accounting it
            #                 would skew concurrency and poison the
            #                 limiter floor (shed != method failure)
            send_response()
            cntl._maybe_recycle()
            return
        server_counted[0] = True
        if md is None:
            cntl.set_failed(errors.ENOMETHOD if req_meta.service_name in
                            server.services() else errors.ENOSERVICE,
                            f"no method {full_name}")
            send_response()
            cntl._maybe_recycle()
            return
        if status is not None and not status.on_requested():
            cntl.set_failed(errors.ELIMIT,
                            f"method {full_name} max_concurrency reached")
            status = None           # don't on_responded a rejected request
            send_response()
            cntl._maybe_recycle()
            return
        # auth (reference: protocol verify hook)
        if server.options.auth is not None:
            if not server.options.auth.verify(cntl.auth_token, socket):
                cntl.set_failed(errors.ERPCAUTH, "authentication failed")
                send_response()
                cntl._maybe_recycle()
                return
        _parse_and_invoke()
        return

    # ---- admission-control path (rpc/admission.py): the gate decision
    # moves into the shared controller — shed-before-queue, per-tenant
    # WFQ, deadline-expired shed — identical on all three call planes
    if md is None:
        cntl.set_failed(errors.ENOMETHOD if req_meta.service_name in
                        server.services() else errors.ENOSERVICE,
                        f"no method {full_name}")
        status = None               # never admitted: nothing to account
        send_response()
        cntl._maybe_recycle()
        return
    from ..rpc import admission as admission_mod

    def _admitted(queued_us: int) -> None:
        server_counted[0] = True
        if stages and queued_us:
            # admission-queue wait feeds the queue-stage decomposition
            _record_stage("queue", queued_us, cntl.span)
        if server.options.auth is not None:
            if not server.options.auth.verify(cntl.auth_token, socket):
                cntl.set_failed(errors.ERPCAUTH, "authentication failed")
                send_response()
                cntl._maybe_recycle()
                return
        _parse_and_invoke()

    def _shed(code: int, text: str, retry_after: int) -> None:
        nonlocal status
        status = None               # shed: no on_requested happened
        cntl.set_failed(code, text)
        if retry_after:
            cntl.retry_after_ms = retry_after
        send_response()
        cntl._maybe_recycle()

    adm.submit(priority=cntl.priority, tenant=cntl.tenant,
               deadline_left_ms=cntl.deadline_left_ms or None,
               recv_us=(msg.recv_ns // 1000) if msg.recv_ns else 0,
               try_enter=admission_mod.server_method_gate(server, status),
               run=_admitted, shed=_shed)


PROTOCOL = Protocol(
    name="tpu_std",
    parse=parse,
    process_request=process_request,
    process_response=process_response,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_inline=process_inline,
)


def _register() -> None:
    from ..rpc.protocol import find_protocol
    if find_protocol("tpu_std") is None:
        register_protocol(PROTOCOL)


_register()
