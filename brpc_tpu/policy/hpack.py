"""HPACK (RFC 7541) — header compression for HTTP/2.

Reference: src/brpc/details/hpack.{h,cpp}.  Full decoder (indexed fields,
all literal forms, dynamic-table size updates, static + dynamic tables)
and a full encoder: ``Encoder()`` defaults to incremental indexing with
its own dynamic table (the RFC's example encoder, golden-pinned against
Appendix C.3-C.6 in tests/test_grpc.py), with optional huffman coding
both directions.

INVARIANT the connection depends on: the default encoder is STATEFUL —
its dynamic table must evolve in the same order the peer's decoder sees
the blocks, so every header block must reach the wire in encode order
(grpc.py holds the h2 conn lock across encode AND write for this reason).
``Encoder(index=False)`` restores the stateless literal-only form.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

STATIC_TABLE: List[Tuple[bytes, bytes]] = [
    (b":authority", b""), (b":method", b"GET"), (b":method", b"POST"),
    (b":path", b"/"), (b":path", b"/index.html"), (b":scheme", b"http"),
    (b":scheme", b"https"), (b":status", b"200"), (b":status", b"204"),
    (b":status", b"206"), (b":status", b"304"), (b":status", b"400"),
    (b":status", b"404"), (b":status", b"500"), (b"accept-charset", b""),
    (b"accept-encoding", b"gzip, deflate"), (b"accept-language", b""),
    (b"accept-ranges", b""), (b"accept", b""), (b"access-control-allow-origin", b""),
    (b"age", b""), (b"allow", b""), (b"authorization", b""),
    (b"cache-control", b""), (b"content-disposition", b""),
    (b"content-encoding", b""), (b"content-language", b""),
    (b"content-length", b""), (b"content-location", b""),
    (b"content-range", b""), (b"content-type", b""), (b"cookie", b""),
    (b"date", b""), (b"etag", b""), (b"expect", b""), (b"expires", b""),
    (b"from", b""), (b"host", b""), (b"if-match", b""),
    (b"if-modified-since", b""), (b"if-none-match", b""), (b"if-range", b""),
    (b"if-unmodified-since", b""), (b"last-modified", b""), (b"link", b""),
    (b"location", b""), (b"max-forwards", b""), (b"proxy-authenticate", b""),
    (b"proxy-authorization", b""), (b"range", b""), (b"referer", b""),
    (b"refresh", b""), (b"retry-after", b""), (b"server", b""),
    (b"set-cookie", b""), (b"strict-transport-security", b""),
    (b"transfer-encoding", b""), (b"user-agent", b""), (b"vary", b""),
    (b"via", b""), (b"www-authenticate", b""),
]

_STATIC_LOOKUP: Dict[Tuple[bytes, bytes], int] = {
    kv: i + 1 for i, kv in enumerate(STATIC_TABLE)}
_STATIC_NAME_LOOKUP: Dict[bytes, int] = {}
for i, (k, _) in enumerate(STATIC_TABLE):
    _STATIC_NAME_LOOKUP.setdefault(k, i + 1)

# RFC 7541 Appendix B huffman code table: (code, bits) per symbol 0..256
_HUFF = [
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12), (0x1ff9, 13),
    (0x15, 6), (0xf8, 8), (0x7fa, 11), (0x3fa, 10), (0x3fb, 10),
    (0xf9, 8), (0x7fb, 11), (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6), (0x1a, 6), (0x1b, 6),
    (0x1c, 6), (0x1d, 6), (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8),
    (0x7ffc, 15), (0x20, 6), (0xffb, 12), (0x3fc, 10), (0x1ffa, 13),
    (0x21, 6), (0x5d, 7), (0x5e, 7), (0x5f, 7), (0x60, 7), (0x61, 7),
    (0x62, 7), (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7), (0x67, 7),
    (0x68, 7), (0x69, 7), (0x6a, 7), (0x6b, 7), (0x6c, 7), (0x6d, 7),
    (0x6e, 7), (0x6f, 7), (0x70, 7), (0x71, 7), (0x72, 7), (0xfc, 8),
    (0x73, 7), (0xfd, 8), (0x1ffb, 13), (0x7fff0, 19), (0x1ffc, 13),
    (0x3ffc, 14), (0x22, 6), (0x7ffd, 15), (0x3, 5), (0x23, 6), (0x4, 5),
    (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6), (0x27, 6), (0x6, 5),
    (0x74, 7), (0x75, 7), (0x28, 6), (0x29, 6), (0x2a, 6), (0x7, 5),
    (0x2b, 6), (0x76, 7), (0x2c, 6), (0x8, 5), (0x9, 5), (0x2d, 6),
    (0x77, 7), (0x78, 7), (0x79, 7), (0x7a, 7), (0x7b, 7), (0x7ffe, 15),
    (0x7fc, 11), (0x3ffd, 14), (0x1ffd, 13), (0xffffffc, 28), (0xfffe6, 20),
    (0x3fffd2, 22), (0xfffe7, 20), (0xfffe8, 20), (0x3fffd3, 22),
    (0x3fffd4, 22), (0x3fffd5, 22), (0x7fffd9, 23), (0x3fffd6, 22),
    (0x7fffda, 23), (0x7fffdb, 23), (0x7fffdc, 23), (0x7fffdd, 23),
    (0x7fffde, 23), (0xffffeb, 24), (0x7fffdf, 23), (0xffffec, 24),
    (0xffffed, 24), (0x3fffd7, 22), (0x7fffe0, 23), (0xffffee, 24),
    (0x7fffe1, 23), (0x7fffe2, 23), (0x7fffe3, 23), (0x7fffe4, 23),
    (0x1fffdc, 21), (0x3fffd8, 22), (0x7fffe5, 23), (0x3fffd9, 22),
    (0x7fffe6, 23), (0x7fffe7, 23), (0xffffef, 24), (0x3fffda, 22),
    (0x1fffdd, 21), (0xfffe9, 20), (0x3fffdb, 22), (0x3fffdc, 22),
    (0x7fffe8, 23), (0x7fffe9, 23), (0x1fffde, 21), (0x7fffea, 23),
    (0x3fffdd, 22), (0x3fffde, 22), (0xfffff0, 24), (0x1fffdf, 21),
    (0x3fffdf, 22), (0x7fffeb, 23), (0x7fffec, 23), (0x1fffe0, 21),
    (0x1fffe1, 21), (0x3fffe0, 22), (0x1fffe2, 21), (0x7fffed, 23),
    (0x3fffe1, 22), (0x7fffee, 23), (0x7fffef, 23), (0xfffea, 20),
    (0x3fffe2, 22), (0x3fffe3, 22), (0x3fffe4, 22), (0x7ffff0, 23),
    (0x3fffe5, 22), (0x3fffe6, 22), (0x7ffff1, 23), (0x3ffffe0, 26),
    (0x3ffffe1, 26), (0xfffeb, 20), (0x7fff1, 19), (0x3fffe7, 22),
    (0x7ffff2, 23), (0x3fffe8, 22), (0x1ffffec, 25), (0x3ffffe2, 26),
    (0x3ffffe3, 26), (0x3ffffe4, 26), (0x7ffffde, 27), (0x7ffffdf, 27),
    (0x3ffffe5, 26), (0xfffff1, 24), (0x1ffffed, 25), (0x7fff2, 19),
    (0x1fffe3, 21), (0x3ffffe6, 26), (0x7ffffe0, 27), (0x7ffffe1, 27),
    (0x3ffffe7, 26), (0x7ffffe2, 27), (0xfffff2, 24), (0x1fffe4, 21),
    (0x1fffe5, 21), (0x3ffffe8, 26), (0x3ffffe9, 26), (0xffffffd, 28),
    (0x7ffffe3, 27), (0x7ffffe4, 27), (0x7ffffe5, 27), (0xfffec, 20),
    (0xfffff3, 24), (0xfffed, 20), (0x1fffe6, 21), (0x3fffe9, 22),
    (0x1fffe7, 21), (0x1fffe8, 21), (0x7ffff3, 23), (0x3fffea, 22),
    (0x3fffeb, 22), (0x1ffffee, 25), (0x1ffffef, 25), (0xfffff4, 24),
    (0xfffff5, 24), (0x3ffffea, 26), (0x7ffff4, 23), (0x3ffffeb, 26),
    (0x7ffffe6, 27), (0x3ffffec, 26), (0x3ffffed, 26), (0x7ffffe7, 27),
    (0x7ffffe8, 27), (0x7ffffe9, 27), (0x7ffffea, 27), (0x7ffffeb, 27),
    (0xffffffe, 28), (0x7ffffec, 27), (0x7ffffed, 27), (0x7ffffee, 27),
    (0x7ffffef, 27), (0x7fffff0, 27), (0x3ffffee, 26), (0x3fffffff, 30),
]

_huff_decode_tree: Optional[dict] = None


def _build_huff_tree() -> dict:
    global _huff_decode_tree
    if _huff_decode_tree is None:
        root: dict = {}
        for sym, (code, bits) in enumerate(_HUFF):
            node = root
            for i in range(bits - 1, -1, -1):
                bit = (code >> i) & 1
                if i == 0:
                    node[bit] = sym
                else:
                    node = node.setdefault(bit, {})
        _huff_decode_tree = root
    return _huff_decode_tree


def huffman_decode(data: bytes) -> bytes:
    tree = _build_huff_tree()
    out = bytearray()
    node = tree
    for byte in data:
        for i in range(7, -1, -1):
            bit = (byte >> i) & 1
            nxt = node[bit]
            if isinstance(nxt, int):
                if nxt == 256:
                    raise ValueError("EOS in huffman stream")
                out.append(nxt)
                node = tree
            else:
                node = nxt
    return bytes(out)


def _encode_int(value: int, prefix_bits: int, first_byte_flags: int) -> bytes:
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([first_byte_flags | value])
    out = [first_byte_flags | limit]
    value -= limit
    while value >= 128:
        out.append((value % 128) + 128)
        value //= 128
    out.append(value)
    return bytes(out)


def _decode_int(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not (b & 0x80):
            return value, pos


def huffman_encode(data: bytes) -> bytes:
    """RFC 7541 §5.2: huffman string, EOS-padded with 1-bits."""
    bits = 0
    nbits = 0
    out = bytearray()
    for b in data:
        code, n = _HUFF[b]
        bits = (bits << n) | code
        nbits += n
        while nbits >= 8:
            nbits -= 8
            out.append((bits >> nbits) & 0xFF)
    if nbits:
        pad = 8 - nbits
        out.append(((bits << pad) | ((1 << pad) - 1)) & 0xFF)
    return bytes(out)


class Encoder:
    """RFC 7541 encoder with its own dynamic table.

    ``index=True`` (default) emits literal-with-incremental-indexing for
    non-static headers, so repeats on a connection compress to 1-2 bytes —
    this is the RFC's own example encoder (Appendix C.3-C.6), and the
    golden-vector tests pin its output byte-for-byte.  ``index=False``
    restores the stateless literal-without-indexing form (never requires
    peer state).  ``use_huffman`` huffman-codes every literal string (the
    C.4/C.6 examples)."""

    def __init__(self, index: bool = True, use_huffman: bool = False,
                 max_table_size: int = 4096):
        self.index = index
        self.use_huffman = use_huffman
        self.dynamic: List[Tuple[bytes, bytes]] = []
        self.max_table_size = max_table_size
        self._size = 0

    # dynamic-table bookkeeping mirrors the Decoder exactly: both ends
    # evolve the same table from the same header stream (RFC 7541 §2.3.2)
    def _add(self, name: bytes, value: bytes) -> None:
        self.dynamic.insert(0, (name, value))
        self._size += len(name) + len(value) + 32
        while self._size > self.max_table_size and self.dynamic:
            n, v = self.dynamic.pop()
            self._size -= len(n) + len(v) + 32

    def table_size(self) -> int:
        return self._size

    def _find(self, name: bytes, value: bytes) -> Tuple[int, bool]:
        """(index, full_match); index 0 = no name match anywhere."""
        idx = _STATIC_LOOKUP.get((name, value))
        if idx is not None:
            return idx, True
        for i, (n, v) in enumerate(self.dynamic):
            if n == name and v == value:
                return len(STATIC_TABLE) + 1 + i, True
        name_idx = _STATIC_NAME_LOOKUP.get(name, 0)
        if name_idx == 0:
            for i, (n, _v) in enumerate(self.dynamic):
                if n == name:
                    name_idx = len(STATIC_TABLE) + 1 + i
                    break
        return name_idx, False

    def _string(self, s: bytes) -> bytes:
        if self.use_huffman:
            enc = huffman_encode(s)
            return _encode_int(len(enc), 7, 0x80) + enc
        return _encode_int(len(s), 7, 0x00) + s

    def encode(self, headers: List[Tuple[bytes, bytes]]) -> bytes:
        out = bytearray()
        for name, value in headers:
            name = name.lower()
            idx, full = self._find(name, value)
            if full:
                out += _encode_int(idx, 7, 0x80)       # indexed field
                continue
            if self.index:
                out += _encode_int(idx, 6, 0x40)       # incremental indexing
                if idx == 0:
                    out += self._string(name)
                out += self._string(value)
                self._add(name, value)
            else:
                out += _encode_int(idx, 4, 0x00)       # literal, no indexing
                if idx == 0:
                    out += self._string(name)
                out += self._string(value)
        return bytes(out)


class Decoder:
    def __init__(self, max_table_size: int = 4096):
        self.dynamic: List[Tuple[bytes, bytes]] = []
        self.max_table_size = max_table_size
        self._size = 0

    def _entry(self, index: int) -> Tuple[bytes, bytes]:
        if 1 <= index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        d = index - len(STATIC_TABLE) - 1
        if 0 <= d < len(self.dynamic):
            return self.dynamic[d]
        raise ValueError(f"bad hpack index {index}")

    def _add(self, name: bytes, value: bytes) -> None:
        self.dynamic.insert(0, (name, value))
        self._size += len(name) + len(value) + 32
        while self._size > self.max_table_size and self.dynamic:
            n, v = self.dynamic.pop()
            self._size -= len(n) + len(v) + 32

    def _read_string(self, data: bytes, pos: int) -> Tuple[bytes, int]:
        huff = bool(data[pos] & 0x80)
        length, pos = _decode_int(data, pos, 7)
        raw = data[pos:pos + length]
        pos += length
        return (huffman_decode(raw) if huff else raw), pos

    def decode(self, data: bytes) -> List[Tuple[bytes, bytes]]:
        out: List[Tuple[bytes, bytes]] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:                    # indexed
                index, pos = _decode_int(data, pos, 7)
                out.append(self._entry(index))
            elif b & 0x40:                  # literal with incremental indexing
                index, pos = _decode_int(data, pos, 6)
                if index:
                    name = self._entry(index)[0]
                else:
                    name, pos = self._read_string(data, pos)
                value, pos = self._read_string(data, pos)
                self._add(name, value)
                out.append((name, value))
            elif b & 0x20:                  # dynamic table size update
                size, pos = _decode_int(data, pos, 5)
                self.max_table_size = size
                while self._size > size and self.dynamic:
                    n, v = self.dynamic.pop()
                    self._size -= len(n) + len(v) + 32
            else:                           # literal w/o indexing (or never)
                index, pos = _decode_int(data, pos, 4)
                if index:
                    name = self._entry(index)[0]
                else:
                    name, pos = self._read_string(data, pos)
                value, pos = self._read_string(data, pos)
                out.append((name, value))
        return out
