"""HTTP/1.1 protocol: JSON access to pb services + the builtin admin pages.

Reference: src/brpc/policy/http_rpc_protocol.cpp (+ details/http_parser,
http_message) — the same server port speaks HTTP next to tpu_std thanks to
protocol detection (text method prefix vs "TRPC" magic).  Routes:

  * ``POST /ServiceName/MethodName`` with a JSON body → the pb method
    (json2pb both ways), mirroring the reference's /Service/Method mapping.
  * ``GET /status|/vars|/flags|/connections|/rpcz|/brpc_metrics|...`` →
    builtin admin pages (builtin/services.py).
  * anything else → 404.

Client side: ``Channel.init(..., options.protocol="http")`` issues HTTP
requests with pb-JSON bodies and parses responses, completing the same
Controller machinery (correlation by pipeline order — HTTP/1.1 on one
connection answers in order, the reference's behavior without h2).
"""
from __future__ import annotations

import json
import time
import urllib.parse
from typing import Any, Dict, Optional

from ..butil.containers import CaseIgnoredFlatMap
from ..butil.iobuf import IOBuf
from ..codec import json2pb
from ..proto import rpc_meta_pb2 as meta_pb
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import Protocol, ParseResult, register_protocol

_METHODS = (b"GET ", b"POST", b"PUT ", b"DELE", b"HEAD", b"OPTI", b"PATC",
            b"HTTP")


class HttpMessage:
    def __init__(self):
        self.is_request = True
        self.method = "GET"
        self.path = "/"
        self.query: Dict[str, str] = {}
        self.status = 200
        self.reason = "OK"
        self.headers: CaseIgnoredFlatMap = CaseIgnoredFlatMap()
        self.body = b""


def _parse_http(source: IOBuf) -> ParseResult:
    head = source.fetch(4)
    if head is None:
        return ParseResult.not_enough_data()
    if not any(head == m[:len(head)] or head.startswith(m.strip())
               for m in _METHODS):
        return ParseResult.try_others()
    data = source.fetch(len(source))
    sep = data.find(b"\r\n\r\n")
    if sep < 0:
        if len(data) > 1 << 20:
            return ParseResult.parse_error("header too large")
        return ParseResult.not_enough_data()
    header_bytes = data[:sep]
    lines = header_bytes.split(b"\r\n")
    msg = HttpMessage()
    first = lines[0].decode("latin1")
    parts = first.split(" ")
    if first.startswith("HTTP/"):
        msg.is_request = False
        msg.status = int(parts[1])
        msg.reason = " ".join(parts[2:]) if len(parts) > 2 else ""
    else:
        msg.is_request = True
        msg.method = parts[0]
        target = parts[1] if len(parts) > 1 else "/"
        parsed = urllib.parse.urlsplit(target)
        msg.path = parsed.path
        msg.query = dict(urllib.parse.parse_qsl(parsed.query))
    for line in lines[1:]:
        k, _, v = line.decode("latin1").partition(":")
        msg.headers[k.strip()] = v.strip()
    te = msg.headers.get("Transfer-Encoding", "")
    if te:
        # RFC 7230 §4.1 chunked coding, both directions (requests from
        # curl-style clients that stream bodies of unknown length, and
        # responses from chunked-emitting servers) — the last VERDICT
        # "Content-Length-only" gap.  Token-exact: 'gzip, chunked' (a
        # coding we cannot decode) or 'xchunked' must be REJECTED, not
        # substring-matched into ambiguous framing (§3.3.3 — the
        # smuggling shape), and chunked combined with anything else is
        # unsupported here.
        tokens = [t.strip().lower() for t in te.split(",") if t.strip()]
        if tokens != ["chunked"]:
            return ParseResult.parse_error(
                f"unsupported transfer-encoding {te!r}")
        body, total = _parse_chunked_body(data, sep + 4)
        if total < 0:
            return ParseResult.parse_error("bad chunked framing")
        if body is None:
            return ParseResult.not_enough_data()
        msg.body = body
        source.pop_front(total)
        return ParseResult.ok(msg)
    length = int(msg.headers.get("Content-Length", "0") or 0)
    total = sep + 4 + length
    if len(data) < total:
        return ParseResult.not_enough_data()
    msg.body = data[sep + 4:total]
    source.pop_front(total)
    return ParseResult.ok(msg)


def _parse_chunked_body(data: bytes, off: int):
    """Decode a chunked body starting at ``off``.  Returns
    ``(body, total_consumed)``; ``(None, 0)`` when incomplete;
    ``(None, -1)`` on malformed framing.  Trailer headers (RFC 7230
    §4.1.2) are consumed and discarded."""
    out = []
    while True:
        nl = data.find(b"\r\n", off)
        if nl < 0:
            return None, 0
        size_token = data[off:nl].split(b";", 1)[0].strip()  # drop ext
        # pure-hex only: int(x, 16) would also accept '-2' / '+5' /
        # '0x10' / '1_0', and a negative size desyncs framing against
        # any strict RFC 7230 peer — the request-smuggling shape
        if not size_token or any(c not in b"0123456789abcdefABCDEF"
                                 for c in size_token):
            return None, -1
        size = int(size_token, 16)
        off = nl + 2
        if size == 0:
            break
        if len(data) < off + size + 2:
            return None, 0
        out.append(data[off:off + size])
        if data[off + size:off + size + 2] != b"\r\n":
            return None, -1
        off += size + 2
    # trailer section: zero or more header lines, then the empty line
    while True:
        nl = data.find(b"\r\n", off)
        if nl < 0:
            return None, 0
        if nl == off:                      # empty line: body complete
            return b"".join(out), nl + 2
        off = nl + 2


def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    return _parse_http(source)


def _render_response(status: int, body: bytes, content_type: str,
                     extra_headers: Optional[Dict[str, str]] = None,
                     chunked: bool = False) -> IOBuf:
    reason = {200: "OK", 400: "Bad Request", 401: "Unauthorized",
              403: "Forbidden", 404: "Not Found",
              500: "Internal Server Error", 503: "Service Unavailable"}.get(
                  status, "OK")
    out = IOBuf()
    head = [f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {content_type}"]
    if chunked:
        head.append("Transfer-Encoding: chunked")
    else:
        head.append(f"Content-Length: {len(body)}")
    for k, v in (extra_headers or {}).items():
        head.append(f"{k}: {v}")
    out.append(("\r\n".join(head) + "\r\n\r\n").encode())
    if chunked:
        out.append(_encode_chunked(body))
    else:
        out.append(body)
    return out


def _encode_chunked(body: bytes) -> bytes:
    """RFC 7230 §4.1 chunked framing.  The body is split into at least
    two chunks when possible so receivers exercise real re-assembly, not
    the one-chunk degenerate case."""
    chunks = []
    if len(body) > 1:
        half = len(body) // 2
        chunks = [body[:half], body[half:]]
    elif body:
        chunks = [body]
    out = []
    for c in chunks:
        out.append(b"%x\r\n" % len(c))
        out.append(c)
        out.append(b"\r\n")
    out.append(b"0\r\n\r\n")
    return b"".join(out)


# ---- server side ------------------------------------------------------

def process_request(msg: HttpMessage, socket, server) -> None:
    start_us = time.monotonic_ns() // 1000
    path = msg.path.strip("/")
    internal_conn = getattr(socket, "internal_only", False)
    # 1) builtin pages.  With ServerOptions.internal_port set, admin
    # pages move to THAT port exclusively (reference server.h
    # internal_port: "only accessible from internal_port") — the public
    # port refuses them, and the internal port serves nothing else.
    builtin = getattr(server, "_builtin", None)
    if builtin is not None:
        admin_here = internal_conn or server.options.internal_port < 0
        if admin_here:
            hit = builtin.dispatch(path or "index", dict(msg.query))
            if hit is not None:
                # 2-tuple = 200; 3-tuple carries an explicit status
                # (/health → 503 while draining)
                status, (ctype, body) = (200, hit) if len(hit) == 2 \
                    else (hit[0], hit[1:])
                socket.write(_render_response(status, body.encode(), ctype))
                return
        elif (path or "index") in builtin.handlers:
            # dispatch() can have side effects (/flags, /vlog): refuse by
            # path membership, never by probing
            socket.write(_render_response(
                403, b'{"error":"builtin services are only served on '
                     b'the internal port"}', "application/json"))
            return
    if internal_conn:
        # the admin port serves ONLY builtin pages
        socket.write(_render_response(
            403, b'{"error":"user services are not served on the '
                 b'internal port"}', "application/json"))
        return
    # 2) restful mappings (reference restful.{h,cpp})
    mapped = server.options.restful_mappings.get("/" + path)
    if mapped is not None:
        md = server.find_method(mapped)
        if md is not None:
            _process_json_rpc(msg, socket, server, md, mapped, start_us)
            return
    # 3) /Service/Method JSON RPC
    parts = [p for p in path.split("/") if p]
    if len(parts) == 2:
        full_name = f"{parts[0]}.{parts[1]}"
        md = server.find_method(full_name)
        if md is not None:
            _process_json_rpc(msg, socket, server, md, full_name, start_us)
            return
    socket.write(_render_response(
        404, json.dumps({"error": f"no handler for /{path}"}).encode(),
        "application/json"))


def json_rpc_dispatch(server, md, full_name: str, body: str, send,
                      start_us: int, cntl: Optional[Controller] = None
                      ) -> None:
    """JSON-RPC dispatch shared by HTTP/1 and h2 REST (policy/grpc.py):
    method-status accounting, json2pb both directions, and the error-JSON
    shapes, with ``send(code, body_bytes)`` as the transport-specific
    responder.  ``send`` is called exactly once."""
    if cntl is None:
        cntl = Controller()
    cntl.server = server
    if getattr(server, "is_draining", lambda: False)():
        # lame-duck: same contract as tpu_std — the rpc-aware http
        # client maps the code back to retryable ELOGOFF and fails over
        send(503, json.dumps({"error": "server is draining (lame duck)",
                              "code": errors.ELOGOFF}).encode())
        return
    status = server.method_status(full_name)
    if status is not None and not status.on_requested():
        send(503, b'{"error":"concurrency limit"}')
        return

    def finish(code: int, body_bytes: bytes) -> None:
        send(code, body_bytes)
        if status is not None:
            status.on_responded(0 if code == 200 else code,
                                time.monotonic_ns() // 1000 - start_us)

    ok, request, err = json2pb.json_to_pb(body, md.request_cls)
    if not ok:
        finish(400, json.dumps({"error": f"bad request JSON: {err}"}).encode())
        return
    response = md.response_cls()
    done_called = [False]

    def done() -> None:
        if done_called[0]:
            return
        done_called[0] = True
        if cntl.failed():
            finish(500, json.dumps({"error": cntl.error_text_,
                                    "code": cntl.error_code_}).encode())
        else:
            ok2, js = json2pb.pb_to_json(response)
            finish(200 if ok2 else 500, js.encode())

    cntl.set_server_done(done)
    try:
        md.invoke(cntl, request, response, done)
    except Exception as e:
        if not done_called[0]:
            cntl.set_failed(errors.EINTERNAL, f"{type(e).__name__}: {e}")
            done()


def _process_json_rpc(msg: HttpMessage, socket, server, md, full_name,
                      start_us) -> None:
    cntl = Controller()
    cntl.remote_side = socket.remote_side
    body = msg.body.decode("utf-8", "replace") if msg.body else "{}"
    if msg.is_request and msg.method == "GET" and msg.query:
        body = json.dumps(msg.query)
    # a chunked request is answered chunked: the deterministic echo rule
    # that lets one round trip prove BOTH the parse and emit directions
    # (the parser already rejected any TE other than a lone 'chunked')
    chunked = (msg.headers.get("Transfer-Encoding", "")
               .strip().lower() == "chunked")

    def send(code: int, body_bytes: bytes) -> None:
        socket.write(_render_response(code, body_bytes, "application/json",
                                      chunked=chunked))

    json_rpc_dispatch(server, md, full_name, body, send, start_us, cntl)


# ---- client side ------------------------------------------------------

def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    buf = IOBuf()
    if request is None:
        return buf
    if hasattr(request, "SerializeToString"):
        ok, js = json2pb.pb_to_json(request)
        if not ok:
            raise ValueError(f"cannot jsonify request: {js}")
        buf.append(js)
    elif isinstance(request, (bytes, bytearray, str)):
        buf.append(request)
    else:
        buf.append(json.dumps(request))
    return buf


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    service, _, method = method_full_name.rpartition(".")
    body = payload.to_bytes()
    out = IOBuf()
    host = str(cntl.remote_side) if cntl.remote_side else "localhost"
    out.append(f"POST /{service}/{method} HTTP/1.1\r\n"
               f"Host: {host}\r\n"
               f"Content-Type: application/json\r\n"
               f"Content-Length: {len(body)}\r\n"
               f"X-Correlation-Id: {cid}\r\n\r\n".encode())
    out.append(body)
    return out


def process_response(msg: HttpMessage, socket) -> None:
    """HTTP/1.1 single connection answers in order: correlate with the
    oldest in-flight call on this socket (pipelined_contexts)."""
    from ..bthread import id as bthread_id
    ctx = socket.pop_pipelined_context()
    if ctx is None:
        return
    cid = ctx
    rc, cntl = bthread_id.lock(cid)
    if rc != 0 or cntl is None:
        return
    meta = meta_pb.RpcMeta()
    if msg.status != 200:
        try:
            err = json.loads(msg.body or b"{}")
        except Exception:
            err = {}
        meta.response.error_code = int(err.get("code", errors.EHTTP))
        meta.response.error_text = err.get("error",
                                           f"HTTP {msg.status} {msg.reason}")
        cntl.handle_response(cid, meta, IOBuf())
        return
    if cntl._response_cls is not None:
        ok, resp, err = json2pb.json_to_pb(
            msg.body.decode("utf-8", "replace"), cntl._response_cls)
        if not ok:
            meta.response.error_code = errors.ERESPONSE
            meta.response.error_text = f"bad response JSON: {err}"
            cntl.handle_response(cid, meta, IOBuf())
            return
        cntl.response = resp
        cntl._parsed_response = resp
    body = IOBuf()
    body.append(msg.body)
    cntl._http_ok_body = msg.body
    cntl.handle_parsed_http_response(cid, msg)


PROTOCOL = Protocol(
    name="http",
    parse=parse,
    process_request=process_request,
    process_response=process_response,
    serialize_request=serialize_request,
    pack_request=pack_request,
    pipelined=True,
)


def _register() -> None:
    from ..rpc.protocol import find_protocol
    if find_protocol("http") is None:
        register_protocol(PROTOCOL)


_register()
