"""Concurrency limiters (reference: src/brpc/policy/ — constant,
auto_concurrency_limiter.{h,cpp}, timeout_concurrency_limiter.{h,cpp};
interface concurrency_limiter.h:29-44).

* Constant: fixed max concurrent requests.
* Auto: gradient limiter — tracks min latency (no-load) vs sampled latency
  and adapts max_concurrency toward peak qps × min_latency, the algorithm
  described in docs/cn/auto_concurrency_limiter.md (re-derived: EMA of
  latency, multiplicative expand/shrink against the latency ratio).
* Timeout: admit while expected queueing delay stays under the deadline.
"""
from __future__ import annotations

import threading
import time


class ConcurrencyLimiter:
    def on_requested(self, current_concurrency: int) -> bool:
        raise NotImplementedError

    def on_responded(self, error_code: int, latency_us: int) -> None:
        pass

    def max_concurrency(self) -> int:
        raise NotImplementedError


class ConstantConcurrencyLimiter(ConcurrencyLimiter):
    def __init__(self, max_concurrency: int):
        self._max = max_concurrency

    def on_requested(self, current_concurrency: int) -> bool:
        return current_concurrency < self._max

    def max_concurrency(self) -> int:
        return self._max


class AutoConcurrencyLimiter(ConcurrencyLimiter):
    ALPHA_FACTOR_ON_DECR = 0.75
    MIN_LIMIT = 4

    def __init__(self, initial: int = 40, sample_window_s: float = 0.1,
                 min_sample_count: int = 20):
        self._max = initial
        self._lock = threading.Lock()
        self._win_start = time.monotonic()
        self._win_lat_sum = 0
        self._win_count = 0
        self._win_err = 0
        self._min_latency_us = None     # EMA of the best observed latency
        self._ema_peak_qps = 0.0
        self._sample_window_s = sample_window_s
        self._min_sample_count = min_sample_count

    def on_requested(self, current_concurrency: int) -> bool:
        return current_concurrency < self._max

    def on_responded(self, error_code: int, latency_us: int) -> None:
        with self._lock:
            now = time.monotonic()
            if error_code == 0:
                self._win_lat_sum += latency_us
                self._win_count += 1
            else:
                self._win_err += 1
            span = now - self._win_start
            if span < self._sample_window_s or self._win_count < 1:
                return
            if self._win_count < self._min_sample_count and span < 1.0:
                return
            avg_latency = self._win_lat_sum / self._win_count
            qps = self._win_count / span
            if self._min_latency_us is None:
                self._min_latency_us = avg_latency
            else:
                # latency floor decays slowly so a quiet period can lower it
                self._min_latency_us = min(self._min_latency_us * 1.02,
                                           avg_latency,
                                           self._min_latency_us)
            self._ema_peak_qps = max(self._ema_peak_qps * 0.98, qps)
            # ideal concurrency ≈ peak_qps × min_latency (Little's law)
            ideal = self._ema_peak_qps * (self._min_latency_us / 1e6)
            ratio = avg_latency / max(self._min_latency_us, 1e-9)
            if ratio > 1.5:     # overloaded: shrink toward ideal
                newmax = max(int(ideal * self.ALPHA_FACTOR_ON_DECR),
                             self.MIN_LIMIT)
            else:               # healthy: probe upward
                newmax = max(int(max(ideal, self._max) * 1.1) + 1,
                             self.MIN_LIMIT)
            self._max = newmax
            self._win_start = now
            self._win_lat_sum = self._win_count = self._win_err = 0

    def max_concurrency(self) -> int:
        return self._max


class TimeoutConcurrencyLimiter(ConcurrencyLimiter):
    """Admit while estimated queue wait < timeout budget
    (timeout_concurrency_limiter.cpp)."""

    def __init__(self, timeout_ms: float = 500.0):
        self._timeout_ms = timeout_ms
        self._avg_latency_us = 1000.0
        self._lock = threading.Lock()

    def on_requested(self, current_concurrency: int) -> bool:
        with self._lock:
            expected_wait_ms = current_concurrency * self._avg_latency_us / 1000.0
            return expected_wait_ms < self._timeout_ms

    def on_responded(self, error_code: int, latency_us: int) -> None:
        if error_code == 0:
            with self._lock:
                self._avg_latency_us = (self._avg_latency_us * 0.9
                                        + latency_us * 0.1)

    def max_concurrency(self) -> int:
        with self._lock:
            return max(int(self._timeout_ms * 1000 / max(self._avg_latency_us, 1)), 1)
