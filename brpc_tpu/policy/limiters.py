"""Concurrency limiters (reference: src/brpc/policy/ — constant,
auto_concurrency_limiter.{h,cpp}, timeout_concurrency_limiter.{h,cpp};
interface concurrency_limiter.h:29-44).

* Constant: fixed max concurrent requests.
* Auto: the reference's GRADIENT limiter (docs/cn/
  auto_concurrency_limiter.md): latency/qps are aggregated over sampling
  windows, the no-load latency floor is learned by a noise-filtered EMA
  of window averages (plus periodic forced exploration windows that
  shrink concurrency so the floor can be re-measured under light load),
  and the limit follows the documented gradient formula

      max_concurrency = max_qps × ((2 + alpha) × min_latency − latency)

  which equals peak_qps × min_latency × (1 + alpha) at the knee (Little's
  law with headroom) and walks the limit DOWN linearly as sampled latency
  inflates past the floor.
* Timeout: admit while expected queueing delay stays under the deadline.
"""
from __future__ import annotations

import threading
import time
from typing import Optional


class ConcurrencyLimiter:
    def on_requested(self, current_concurrency: int) -> bool:
        raise NotImplementedError

    def on_responded(self, error_code: int, latency_us: int) -> None:
        pass

    def max_concurrency(self) -> int:
        raise NotImplementedError


class ConstantConcurrencyLimiter(ConcurrencyLimiter):
    def __init__(self, max_concurrency: int):
        self._max = max_concurrency

    def on_requested(self, current_concurrency: int) -> bool:
        return current_concurrency < self._max

    def max_concurrency(self) -> int:
        return self._max


class AutoConcurrencyLimiter(ConcurrencyLimiter):
    """The reference gradient algorithm (auto_concurrency_limiter.cpp,
    re-derived from docs/cn/auto_concurrency_limiter.md — the C++ source
    is not vendored here):

      * samples aggregate into windows of [min_sample_count,
        max_sample_count] responses spanning at least sample_window_us;
      * failed responses contribute fail_punish_ratio × their latency to
        the window's latency mass but not to its success count;
      * min_latency (the no-load floor) moves by EMA only when a window
        beats it, and drifts up very slowly otherwise so a genuinely
        changed baseline re-converges (noise filtering);
      * max_qps rises instantly to any observed peak and decays by a
        slow EMA;
      * every remeasure_interval_us the limiter forces an EXPLORATION
        window: concurrency drops to reduce_ratio × (max_qps ×
        min_latency) and the floor is re-seeded from what it measures —
        without this, a floor learned under load never falls back;
      * otherwise: max_concurrency = max_qps × ((2 + alpha) ×
        min_latency − latency), floored at MIN_LIMIT.

    Timestamps are injectable (``add_sample(..., now_us=...)``) so the
    convergence tests drive a simulated clock deterministically."""

    EMA_FACTOR = 0.1
    ALPHA = 0.3                  # acceptable latency headroom above floor
    FAIL_PUNISH_RATIO = 1.0
    REDUCE_RATIO_WHILE_REMEASURE = 0.9
    MIN_LIMIT = 4

    def __init__(self, initial: int = 40,
                 sample_window_us: int = 100_000,
                 min_sample_count: int = 20,
                 max_sample_count: int = 200,
                 remeasure_interval_us: int = 5_000_000):
        self._max = initial
        self._lock = threading.Lock()
        self._sample_window_us = sample_window_us
        self._min_sample_count = min_sample_count
        self._max_sample_count = max_sample_count
        self._remeasure_interval_us = remeasure_interval_us
        self._win_start_us: Optional[int] = None
        self._win_succ_us = 0
        self._win_fail_us = 0
        self._win_succ = 0
        self._win_fail = 0
        self.min_latency_us: Optional[float] = None
        self.max_qps = 0.0
        self._next_remeasure_us: Optional[int] = None
        self._remeasuring = False
        self.remeasure_count = 0     # exploration windows run (test hook)

    def on_requested(self, current_concurrency: int) -> bool:
        return current_concurrency < self._max

    def on_responded(self, error_code: int, latency_us: int) -> None:
        self.add_sample(error_code, latency_us,
                        time.monotonic_ns() // 1000)

    def add_sample(self, error_code: int, latency_us: int,
                   now_us: int) -> None:
        with self._lock:
            if self._win_start_us is None:
                self._win_start_us = now_us
                self._next_remeasure_us = (self._next_remeasure_us
                                           or now_us
                                           + self._remeasure_interval_us)
            if error_code == 0:
                self._win_succ += 1
                self._win_succ_us += latency_us
            else:
                self._win_fail += 1
                self._win_fail_us += latency_us
            total = self._win_succ + self._win_fail
            span = now_us - self._win_start_us
            if total < self._max_sample_count and (
                    span < self._sample_window_us
                    or total < self._min_sample_count):
                return
            if self._win_succ == 0:
                # an all-error window teaches nothing about latency:
                # shrink defensively and restart the window
                self._max = max(self._max // 2, self.MIN_LIMIT)
                self._reset_window(now_us)
                return
            punished = (self._win_succ_us
                        + self.FAIL_PUNISH_RATIO * self._win_fail_us)
            avg_latency = punished / self._win_succ
            qps = 1e6 * self._win_succ / max(span, 1)
            self._update_min_latency(avg_latency)
            self._update_max_qps(qps)
            if self._remeasuring:
                # exploration done: the floor was re-seeded from a
                # lightly-loaded window; restore the gradient limit
                self._remeasuring = False
                self._max = self._gradient_limit(avg_latency)
            elif self._next_remeasure_us is not None \
                    and now_us >= self._next_remeasure_us:
                # periodic forced exploration: drop concurrency BELOW
                # the knee so the next window samples the no-load floor
                # — sized from the FLOOR (max_qps × min_latency is the
                # knee by Little's law), not from the loaded avg_latency,
                # which under steady overload sits above the knee and
                # would leave the "exploration" window still saturated
                self.remeasure_count += 1
                self._remeasuring = True
                ideal = self.max_qps * (
                    (self.min_latency_us or avg_latency) / 1e6)
                self.min_latency_us = None       # re-learn from scratch
                self._next_remeasure_us = (now_us
                                           + self._remeasure_interval_us)
                self._max = max(
                    int(ideal * self.REDUCE_RATIO_WHILE_REMEASURE),
                    self.MIN_LIMIT)
            else:
                self._max = self._gradient_limit(avg_latency)
            self._reset_window(now_us)

    def _reset_window(self, now_us: int) -> None:
        self._win_start_us = now_us
        self._win_succ = self._win_fail = 0
        self._win_succ_us = self._win_fail_us = 0

    def _update_min_latency(self, avg_latency: float) -> None:
        if self.min_latency_us is None:
            self.min_latency_us = avg_latency
        elif avg_latency < self.min_latency_us:
            # noise filter: move toward a better floor by EMA, never jump
            self.min_latency_us += self.EMA_FACTOR * (
                avg_latency - self.min_latency_us)
        else:
            # very slow upward drift: a permanently slower baseline
            # eventually wins without letting one bad window poison the
            # floor
            self.min_latency_us *= 1.001

    def _update_max_qps(self, qps: float) -> None:
        if qps > self.max_qps:
            self.max_qps = qps
        else:
            self.max_qps += (self.EMA_FACTOR / 10.0) * (qps - self.max_qps)

    def _gradient_limit(self, avg_latency: float) -> int:
        floor = self.min_latency_us or avg_latency
        next_max = self.max_qps / 1e6 * ((2.0 + self.ALPHA) * floor
                                         - avg_latency)
        return max(int(next_max), self.MIN_LIMIT)

    def max_concurrency(self) -> int:
        return self._max


class TimeoutConcurrencyLimiter(ConcurrencyLimiter):
    """Admit while estimated queue wait < timeout budget
    (timeout_concurrency_limiter.cpp)."""

    def __init__(self, timeout_ms: float = 500.0):
        self._timeout_ms = timeout_ms
        self._avg_latency_us = 1000.0
        self._lock = threading.Lock()

    def on_requested(self, current_concurrency: int) -> bool:
        with self._lock:
            expected_wait_ms = current_concurrency * self._avg_latency_us / 1000.0
            return expected_wait_ms < self._timeout_ms

    def on_responded(self, error_code: int, latency_us: int) -> None:
        if error_code == 0:
            with self._lock:
                self._avg_latency_us = (self._avg_latency_us * 0.9
                                        + latency_us * 0.1)

    def max_concurrency(self) -> int:
        with self._lock:
            return max(int(self._timeout_ms * 1000 / max(self._avg_latency_us, 1)), 1)
