"""hulu-pbrpc and sofa-pbrpc: legacy framed protobuf protocols.

Reference behavior:
- src/brpc/policy/hulu_pbrpc_protocol.cpp — frame "HULU" + u32le
  (meta_size+payload_size) + u32le meta_size, then HuluRpcRequestMeta /
  HuluRpcResponseMeta + payload.  Correlation id travels in the meta, so
  single connections work.  Dispatch is by (service_name, method_index)
  with a later method_name override.
- src/brpc/policy/sofa_pbrpc_protocol.cpp — frame "SOFA" + u32le meta_size
  + u64le body_size + u64le total_size, then SofaRpcMeta + body.  One meta
  message for both directions (type=REQUEST|RESPONSE), correlation by
  sequence_id, method addressed by full name.

Both are registered client+server; frames interop with this stack's own
peers (there are no external hulu/sofa speakers to interop with — the value
is the registry exercising two more Protocol shapes, exactly like the
reference keeps them alive as extension examples).
"""
from __future__ import annotations

import time
from types import SimpleNamespace
from typing import Any

from ..butil.iobuf import IOBuf
from ..butil import logging as log
from ..bthread import id as bthread_id
from ..proto import legacy_meta_pb2 as legacy_pb
from ..rpc import errors
from ..rpc import compress as compress_mod
from ..rpc.controller import Controller
from ..rpc.protocol import (Protocol, ParseResult, register_protocol,
                            find_protocol)

HULU_MAGIC = b"HULU"
SOFA_MAGIC = b"SOFA"


class _Frame:
    __slots__ = ("meta", "body")

    def __init__(self, meta, body: IOBuf):
        self.meta = meta
        self.body = body


def _resp_meta_shim(error_code: int, error_text: str, compress_type: int):
    """Adapter so Controller.handle_response (written for tpu_std's RpcMeta)
    can drive retry/parse for legacy metas."""
    return SimpleNamespace(
        response=SimpleNamespace(error_code=error_code,
                                 error_text=error_text),
        attachment_size=0, compress_type=compress_type)


def _serialize_pb(request: Any, cntl: Controller) -> IOBuf:
    if request is None:
        return IOBuf()
    data = request.SerializeToString() if hasattr(request, "SerializeToString") \
        else bytes(request)
    if cntl.compress_type:
        data = compress_mod.compress(cntl.compress_type, data)
    return IOBuf(data)


def _run_method(server, cntl: Controller, md, data: bytes,
                respond) -> None:
    """Shared server tail: parse request, run user code, respond once."""
    try:
        request = md.request_cls()
        request.ParseFromString(data)
    except Exception as e:
        cntl.set_failed(errors.EREQUEST, f"fail to parse request: {e}")
        respond(None)
        return
    response = md.response_cls()
    fired = [False]

    def done() -> None:
        if fired[0]:
            return
        fired[0] = True
        respond(response)

    cntl.set_server_done(done)
    try:
        md.invoke(cntl, request, response, done)
    except Exception as e:
        log.error("method %s raised: %s", md.full_name, e, exc_info=True)
        if not fired[0]:
            cntl.set_failed(errors.EINTERNAL, f"{type(e).__name__}: {e}")
            done()


# ======================================================================
# hulu-pbrpc
# ======================================================================

def _pack_hulu(meta, payload: IOBuf) -> IOBuf:
    meta_bytes = meta.SerializeToString()
    out = IOBuf()
    out.append(HULU_MAGIC)
    out.append((len(meta_bytes) + len(payload)).to_bytes(4, "little"))
    out.append(len(meta_bytes).to_bytes(4, "little"))
    out.append(meta_bytes)
    out.append(payload)
    return out


def hulu_parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    probe = source.fetch(min(len(source), 12))
    if probe is None:
        probe = b""
    if not HULU_MAGIC.startswith(probe[:4]):
        return ParseResult.try_others()
    if len(probe) < 12:
        return ParseResult.not_enough_data()
    body_size = int.from_bytes(probe[4:8], "little")
    meta_size = int.from_bytes(probe[8:12], "little")
    if body_size > (1 << 31):
        return ParseResult.parse_error("absurd hulu body_size")
    if len(source) < 12 + body_size:
        return ParseResult.not_enough_data()
    if meta_size > body_size:
        # recognized-but-invalid frame: fail the connection so the peer
        # sees the breakage (the contract of every magic-claimed parser)
        return ParseResult.parse_error(
            f"hulu meta_size {meta_size} > body_size {body_size}")
    source.pop_front(12)
    meta_buf = source.cut(meta_size)
    payload = source.cut(body_size - meta_size)
    return ParseResult.ok(_Frame(meta_buf.to_bytes(), payload))


def _hulu_find_method(server, meta: legacy_pb.HuluRequestMeta):
    if meta.method_name:
        return server.find_method(f"{meta.service_name}.{meta.method_name}")
    svc = server._services.get(meta.service_name)
    if svc is None:
        return None
    mds = list(svc.methods().values())       # name-sorted: the index space
    if 0 <= meta.method_index < len(mds):
        return server.find_method(mds[meta.method_index].full_name)
    return None


def hulu_process_request(frame: _Frame, socket, server) -> None:
    meta = legacy_pb.HuluRequestMeta()
    try:
        meta.ParseFromString(frame.meta)
    except Exception:
        socket.set_failed(errors.EREQUEST, "bad HuluRequestMeta")
        return
    cid = meta.correlation_id
    start_us = time.monotonic_ns() // 1000
    cntl = Controller()
    cntl.server = server
    cntl.log_id = meta.log_id
    cntl.remote_side = socket.remote_side
    cntl.compress_type = meta.compress_type
    from ..rpc.span import start_server_span, end_server_span
    start_server_span(cntl, f"{meta.service_name}#{meta.method_index}",
                      meta.trace_id, meta.span_id)
    md = _hulu_find_method(server, meta)
    status = server.method_status(md.full_name) if md is not None else None
    counted = [False]

    def respond(resp) -> None:
        rmeta = legacy_pb.HuluResponseMeta()
        rmeta.correlation_id = cid
        rmeta.error_code = cntl.error_code_
        if cntl.error_text_:
            rmeta.error_text = cntl.error_text_
        payload = IOBuf()
        if resp is not None and not cntl.failed():
            data = resp.SerializeToString()
            if meta.compress_type:
                data = compress_mod.compress(meta.compress_type, data)
                rmeta.compress_type = meta.compress_type
            payload.append(data)
        socket.write(_pack_hulu(rmeta, payload))
        if cntl.span is not None:
            end_server_span(cntl)
        if status is not None:
            status.on_responded(cntl.error_code_,
                                time.monotonic_ns() // 1000 - start_us)
        if counted[0]:
            server.on_request_out()

    if not server.on_request_in():
        cntl.set_failed(errors.ELIMIT, "server max_concurrency reached")
        respond(None)
        return
    counted[0] = True
    if md is None:
        cntl.set_failed(errors.ENOMETHOD,
                        f"no method {meta.service_name}#{meta.method_index}")
        respond(None)
        return
    if status is not None and not status.on_requested():
        cntl.set_failed(errors.ELIMIT, f"{md.full_name} concurrency limit")
        status = None
        respond(None)
        return
    data = frame.body.to_bytes()
    if meta.compress_type:
        try:
            data = compress_mod.decompress(meta.compress_type, data)
        except Exception as e:
            cntl.set_failed(errors.EREQUEST, f"bad compressed body: {e}")
            respond(None)
            return
    _run_method(server, cntl, md, data, respond)


def hulu_process_response(frame: _Frame, socket) -> None:
    meta = legacy_pb.HuluResponseMeta()
    try:
        meta.ParseFromString(frame.meta)
    except Exception:
        return
    rc, cntl = bthread_id.lock(meta.correlation_id)
    if rc != 0 or cntl is None:
        return
    cntl.remote_side = socket.remote_side
    cntl.handle_response(meta.correlation_id,
                         _resp_meta_shim(meta.error_code, meta.error_text,
                                         meta.compress_type),
                         frame.body)


def hulu_pack_request(payload: IOBuf, cid: int, cntl: Controller,
                      method_full_name: str) -> IOBuf:
    service, _, method_name = method_full_name.rpartition(".")
    meta = legacy_pb.HuluRequestMeta()
    meta.service_name = service
    meta.method_index = 0                  # method_name takes precedence
    meta.method_name = method_name
    meta.correlation_id = cid
    if cntl.log_id:
        meta.log_id = cntl.log_id
    if cntl.compress_type:
        meta.compress_type = cntl.compress_type
    if cntl.span is not None:
        meta.trace_id = cntl.span.trace_id
        meta.span_id = cntl.span.span_id
        meta.parent_span_id = cntl.span.parent_span_id
    return _pack_hulu(meta, payload)


# ======================================================================
# sofa-pbrpc
# ======================================================================

def _pack_sofa(meta: legacy_pb.SofaRpcMeta, payload: IOBuf) -> IOBuf:
    meta_bytes = meta.SerializeToString()
    out = IOBuf()
    out.append(SOFA_MAGIC)
    out.append(len(meta_bytes).to_bytes(4, "little"))
    out.append(len(payload).to_bytes(8, "little"))
    out.append((len(meta_bytes) + len(payload)).to_bytes(8, "little"))
    out.append(meta_bytes)
    out.append(payload)
    return out


def sofa_parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    probe = source.fetch(min(len(source), 24))
    if probe is None:
        probe = b""
    if not SOFA_MAGIC.startswith(probe[:4]):
        return ParseResult.try_others()
    if len(probe) < 24:
        return ParseResult.not_enough_data()
    meta_size = int.from_bytes(probe[4:8], "little")
    body_size = int.from_bytes(probe[8:16], "little")
    total = int.from_bytes(probe[16:24], "little")
    if total != meta_size + body_size:
        return ParseResult.try_others()
    if body_size > (1 << 31):
        return ParseResult.parse_error("absurd sofa body_size")
    if len(source) < 24 + total:
        return ParseResult.not_enough_data()
    source.pop_front(24)
    meta_buf = source.cut(meta_size)
    payload = source.cut(body_size)
    meta = legacy_pb.SofaRpcMeta()
    try:
        meta.ParseFromString(meta_buf.to_bytes())
    except Exception as e:
        return ParseResult.parse_error(f"bad SofaRpcMeta: {e}")
    return ParseResult.ok(_Frame(meta, payload))


def sofa_process_request(frame: _Frame, socket, server) -> None:
    meta: legacy_pb.SofaRpcMeta = frame.meta
    if meta.type != legacy_pb.SofaRpcMeta.REQUEST:
        return                              # response on a server socket
    seq = meta.sequence_id
    start_us = time.monotonic_ns() // 1000
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = socket.remote_side
    cntl.compress_type = meta.compress_type
    md = server.find_method(meta.method)
    status = server.method_status(md.full_name) if md is not None else None
    counted = [False]

    def respond(resp) -> None:
        rmeta = legacy_pb.SofaRpcMeta()
        rmeta.type = legacy_pb.SofaRpcMeta.RESPONSE
        rmeta.sequence_id = seq
        if cntl.failed():
            rmeta.failed = True
            rmeta.error_code = cntl.error_code_
            rmeta.reason = cntl.error_text_
        payload = IOBuf()
        if resp is not None and not cntl.failed():
            data = resp.SerializeToString()
            want = meta.expected_response_compress_type or meta.compress_type
            if want:
                data = compress_mod.compress(want, data)
                rmeta.compress_type = want
            payload.append(data)
        socket.write(_pack_sofa(rmeta, payload))
        if status is not None:
            status.on_responded(cntl.error_code_,
                                time.monotonic_ns() // 1000 - start_us)
        if counted[0]:
            server.on_request_out()

    if not server.on_request_in():
        cntl.set_failed(errors.ELIMIT, "server max_concurrency reached")
        respond(None)
        return
    counted[0] = True
    if md is None:
        cntl.set_failed(errors.ENOMETHOD, f"no method {meta.method}")
        respond(None)
        return
    if status is not None and not status.on_requested():
        cntl.set_failed(errors.ELIMIT, f"{md.full_name} concurrency limit")
        status = None
        respond(None)
        return
    data = frame.body.to_bytes()
    if meta.compress_type:
        try:
            data = compress_mod.decompress(meta.compress_type, data)
        except Exception as e:
            cntl.set_failed(errors.EREQUEST, f"bad compressed body: {e}")
            respond(None)
            return
    _run_method(server, cntl, md, data, respond)


def sofa_process_response(frame: _Frame, socket) -> None:
    meta: legacy_pb.SofaRpcMeta = frame.meta
    if meta.type != legacy_pb.SofaRpcMeta.RESPONSE:
        return
    rc, cntl = bthread_id.lock(meta.sequence_id)
    if rc != 0 or cntl is None:
        return
    cntl.remote_side = socket.remote_side
    err = meta.error_code if meta.failed else 0
    if meta.failed and err == 0:
        err = errors.EINTERNAL
    cntl.handle_response(meta.sequence_id,
                         _resp_meta_shim(err, meta.reason,
                                         meta.compress_type),
                         frame.body)


def sofa_pack_request(payload: IOBuf, cid: int, cntl: Controller,
                      method_full_name: str) -> IOBuf:
    meta = legacy_pb.SofaRpcMeta()
    meta.type = legacy_pb.SofaRpcMeta.REQUEST
    meta.sequence_id = cid
    meta.method = method_full_name
    if cntl.compress_type:
        meta.compress_type = cntl.compress_type
    return _pack_sofa(meta, payload)


HULU_PROTOCOL = Protocol(
    name="hulu_pbrpc",
    parse=hulu_parse,
    process_request=hulu_process_request,
    process_response=hulu_process_response,
    serialize_request=_serialize_pb,
    pack_request=hulu_pack_request,
)

SOFA_PROTOCOL = Protocol(
    name="sofa_pbrpc",
    parse=sofa_parse,
    process_request=sofa_process_request,
    process_response=sofa_process_response,
    serialize_request=_serialize_pb,
    pack_request=sofa_pack_request,
)


def _register() -> None:
    if find_protocol("hulu_pbrpc") is None:
        register_protocol(HULU_PROTOCOL)
    if find_protocol("sofa_pbrpc") is None:
        register_protocol(SOFA_PROTOCOL)


_register()
