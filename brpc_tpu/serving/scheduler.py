"""Continuous-batching decode scheduler: ONE batched step per tick over
the active session set, sessions admitted and retired BETWEEN steps.

The serving subsystem's execution loop (ROADMAP item 3).  The old
example decoded one-session-per-RPC — every token paid a full RPC and a
full cache walk, and concurrent sessions serialized behind each other.
Here decode is a step loop:

  * **per-step admit/evict** — before every step the scheduler admits
    pending sessions into the roster (strict priority-band order, the
    PR-9 bands) up to ``max_batch``, retires sessions that produced
    their requested tokens, fails queued sessions whose deadline budget
    died waiting, and — when an INTERACTIVE session is pending and the
    roster is full of batch-band work — PREEMPTS the most sheddable
    active session (its progress is preserved; it resumes from its next
    token when a slot frees, bit-exact);
  * **one batched program per step** — the whole roster advances one
    token with one vectorized gather through the paged pool's block
    tables into the per-token reduction arena (``pos_sums_flat``) plus
    a handful of elementwise ops: numpy by default (the 1-core host's
    fastest dispatch), or ONE jit-compiled XLA program per
    (batch, table-width) bucket under ``serving_compiled_step`` — the
    shape a TPU pod runs, parity-pinned against the numpy step;
  * **pins** — every rostered session is pinned in the pool for exactly
    the steps it spends in the roster, so the eviction policy can never
    pull a block table out from under the running program.

Completion callbacks (``emit``/``fail``) run ON the step thread: on
every call plane completion is a response enqueue, never a blocking
write, and the deterministic ordering is what the bit-exactness tests
pin.  The loop thread starts lazily on first submit and parks on its
condvar when idle; ``stop()`` fails everything queued and joins it.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

from .. import bvar
from ..butil import flags as _flags
from ..rpc import errors
from .kv_pool import PagedKvPool

_flags.define_flag(
    "serving_compiled_step", False,
    "run the continuous-batching decode step as ONE jit-compiled XLA "
    "program per (batch, table-width) bucket instead of the numpy "
    "vector step (parity-pinned; numpy dispatches faster on 1-core "
    "CPU hosts, the compiled program is the TPU-pod shape)")


@dataclass
class BatchSchedulerOptions:
    vocab: int                       # the decode recurrence's modulus
    max_batch: int = 64
    bands: int = 4
    default_priority: int = 2
    # bands <= this are "interactive": they may preempt batch-band
    # sessions out of a full roster (progress preserved)
    interactive_priority_max: int = 1
    preempt: bool = True
    # False: no step thread — tests drive step_once() deterministically
    auto_start: bool = True


class StepRequest:
    """One decode request: produce ``steps`` tokens for ``session``.

    Mutable progress (``prev``/``stepi``/``tokens``) lives here so a
    preempted session resumes exactly where it stopped.  ``emit(tokens)``
    / ``fail(code, text, retry_after_ms)`` fire exactly once, on the
    step thread."""

    __slots__ = ("session", "steps", "priority", "tenant", "deadline_us",
                 "emit", "fail", "enq_us", "prev", "stepi", "tokens",
                 "kv", "_done")

    def __init__(self, session: str, steps: int,
                 emit: Callable[[List[int]], None],
                 fail: Callable[[int, str, int], None],
                 priority: Optional[int] = None, tenant: str = "",
                 deadline_us: Optional[int] = None):
        self.session = session
        self.steps = steps
        self.priority = priority
        self.tenant = tenant
        self.deadline_us = deadline_us
        self.emit = emit
        self.fail = fail
        self.enq_us = 0
        self.prev = 0                # resumes carry the live recurrence
        self.stepi = 0
        self.tokens: List[int] = []
        self.kv = None               # _KvSession while rostered
        self._done = False


class ContinuousBatchScheduler:
    """Admit → step → retire, forever.  One per decode worker."""

    _GUARDED_BY = {
        "_pending": "_cv",
        "_active": "_cv",
        "_owned": "_cv",
        "_stopping": "_cv",
        "_thread": "_cv",
    }

    def __init__(self, pool: PagedKvPool,
                 options: BatchSchedulerOptions,
                 now_us: Optional[Callable[[], int]] = None):
        self.pool = pool
        self.options = options
        self._now_us = now_us or (lambda: time.monotonic_ns() // 1000)
        self._cv = threading.Condition()
        self._pending: List[deque] = [deque()
                                      for _ in range(options.bands)]
        self._active: List[StepRequest] = []     # roster, admit order
        # sessions currently owned by the scheduler (pending OR
        # rostered).  A duplicate submit — a retry storm re-issuing a
        # Decode whose first copy is still running — is REFUSED here:
        # two roster entries on one session would let the first
        # completion release the pool blocks the second still gathers
        # through (another tenant's bytes after block reuse)
        self._owned: set = set()
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        # roster numeric arrays (step-thread-owned; rebuilt when the
        # roster changes membership)
        self._dirty = True
        self._tbl = self._seq = self._acc = None
        self._prev = self._stepi = self._rows = None
        self._jit_cache: Dict[tuple, Callable] = {}
        # counters / gauges
        self.steps = bvar.Adder("serving_steps")
        self.tokens_out = bvar.Adder("serving_tokens")
        self.admitted = bvar.Adder("serving_admitted")
        self.retired = bvar.Adder("serving_retired")
        self.preempted = bvar.Adder("serving_preempted")
        self.expired = bvar.Adder("serving_deadline_expired")
        self.rejected = bvar.Adder("serving_rejected")
        self.occupancy = bvar.IntRecorder("serving_batch_occupancy")
        self._rate_lock = threading.Lock()
        self._rate_ema = 0.0         # steps/s EMA
        self._last_step_us = 0

    # ---- submission -----------------------------------------------------
    def submit(self, req: StepRequest) -> None:
        """Queue one decode request.  Admission happens at the next step
        boundary; refusal paths fire ``req.fail`` (on this thread when
        the scheduler is stopping, on the step thread otherwise)."""
        pri = self.options.default_priority if req.priority is None \
            else req.priority
        pri = min(max(pri, 0), self.options.bands - 1)
        req.priority = pri
        req.enq_us = self._now_us()
        duplicate = False
        with self._cv:
            if self._stopping:
                stopped = True
            elif req.session in self._owned:
                stopped = False
                duplicate = True
            else:
                stopped = False
                self._owned.add(req.session)
                self._pending[pri].append(req)
                if self.options.auto_start and self._thread is None:
                    # fablint: thread-quiesced(stop() sets _stopping and notifies; the loop fails leftovers and exits, stop() joins)
                    t = threading.Thread(target=self._run,
                                         name="serving_step_loop",
                                         daemon=True)
                    self._thread = t
                    t.start()
                self._cv.notify()
        if stopped:
            self.rejected << 1
            self._safe_fail(req, errors.ELOGOFF,
                            "decode scheduler stopping", 0)
        elif duplicate:
            self.rejected << 1
            self._safe_fail(req, errors.EREQUEST,
                            f"session {req.session!r} is already "
                            "decoding (duplicate submit refused)", 0)

    # ---- the loop -------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while (not self._stopping
                       and not self._active
                       and not any(self._pending)):
                    self._cv.wait()
                if self._stopping:
                    victims = self._drain_locked()
                    break
            try:
                self.step_once()
            except Exception as e:
                # one bad roster must not wedge the worker forever:
                # fail the CURRENT roster (the failing entry is in it)
                # and keep the loop alive for the pending queue
                from ..butil import logging as log
                log.error("serving: batched step failed", exc_info=True)
                with self._cv:
                    crashed = self._active
                    self._active = []
                    for req in crashed:
                        self._owned.discard(req.session)
                    self._dirty = True
                for req in crashed:
                    self.pool.unpin(req.session)
                    self._safe_fail(
                        req, errors.EINTERNAL,
                        f"batched decode step failed: "
                        f"{type(e).__name__}: {e}", 0)
        for req, (code, text) in victims:
            self._safe_fail(req, code, text, 0)

    # fablint: lock-held(_cv)
    def _drain_locked(self):
        victims = []
        for band in self._pending:
            while band:
                victims.append((band.popleft(),
                                (errors.ELOGOFF,
                                 "decode scheduler stopping")))
        for req in self._active:
            self.pool.unpin(req.session)
            victims.append((req, (errors.ELOGOFF,
                                  "decode scheduler stopping")))
        self._active = []
        self._owned.clear()
        self._dirty = True
        return victims

    def step_once(self) -> int:
        """Admit/evict at the boundary, then run ONE batched step over
        the roster.  Returns the roster size stepped (0 = idle).  The
        test surface for ``auto_start=False`` schedulers; the loop
        thread calls exactly this."""
        admit_events = []
        with self._cv:
            admit_events = self._admit_locked()
            for req, _code, _text, _hint in admit_events:
                self._owned.discard(req.session)
            roster = list(self._active)
        # refusal callbacks fire outside the lock, in decision order
        for req, code, text, hint in admit_events:
            self._safe_fail(req, code, text, hint)
        if not roster:
            return 0
        self._step_roster(roster)
        # retire finished sessions at the step boundary
        finished = [r for r in roster if len(r.tokens) >= r.steps]
        if finished:
            with self._cv:
                for req in finished:
                    if req in self._active:
                        self._active.remove(req)
                    self._owned.discard(req.session)
                self._dirty = True
            for req in finished:
                self.pool.unpin(req.session)
                self.retired << 1
                req._done = True
                self._safe_emit(req)
        self.steps << 1
        self.occupancy << len(roster)
        now = self._now_us()
        with self._rate_lock:
            if self._last_step_us:
                dt = max(now - self._last_step_us, 1)
                inst = 1e6 / dt
                self._rate_ema = (inst if self._rate_ema == 0.0
                                  else 0.98 * self._rate_ema
                                  + 0.02 * inst)
            self._last_step_us = now
        return len(roster)

    # fablint: lock-held(_cv)
    def _admit_locked(self):
        """Fill the roster from the band queues (strict priority order),
        expire dead deadlines, preempt batch work for interactive
        arrivals.  Returns [(req, code, text, retry_after)] refusals to
        fire outside the lock."""
        o = self.options
        refusals = []
        now = self._now_us()
        for band in self._pending:
            kept = None
            while band:
                req = band.popleft()
                if req.deadline_us is not None and now >= req.deadline_us:
                    self.expired << 1
                    refusals.append((req, errors.ERPCTIMEDOUT,
                                     "decode deadline expired in batch "
                                     "queue", 0))
                    continue
                if len(self._active) >= o.max_batch:
                    kept = req
                    break
                code_text = self._roster_add(req)
                if code_text is not None:
                    refusals.append((req, *code_text))
            if kept is not None:
                band.appendleft(kept)
                break
        # preemption: an interactive arrival blocked by a full roster
        # bumps the most sheddable batch session (progress preserved)
        if o.preempt:
            while (len(self._active) >= o.max_batch
                   and self._interactive_waiting_locked()):
                victim = self._pick_preempt_locked()
                if victim is None:
                    break
                self._active.remove(victim)
                self._dirty = True
                self.pool.unpin(victim.session)
                victim.kv = None
                self._pending[victim.priority].appendleft(victim)
                self.preempted << 1
                nxt = self._pop_interactive_locked(now, refusals)
                if nxt is None:
                    break
                code_text = self._roster_add(nxt)
                if code_text is not None:
                    refusals.append((nxt, *code_text))
        return refusals

    # fablint: lock-held(_cv)
    def _roster_add(self, req: StepRequest):
        """Pin + roster one admitted request; returns (code, text,
        hint) on refusal, None on success."""
        kv = self.pool.get(req.session)
        # fablint: custody-moved(decode-roster) the pin rides req into _active; every roster exit (completion, shed, deadline expiry, drain) unpins before dropping the request
        if kv is None or not self.pool.pin(req.session):
            reason = self.pool.evicted_reason(req.session)
            self.rejected << 1
            if reason is not None:
                return (errors.ELIMIT,
                        f"kv {reason}-evicted: re-prefill the session",
                        1)
            return (errors.EREQUEST,
                    f"unknown session {req.session!r}", 0)
        req.kv = kv
        if not req.tokens and req.stepi == 0:
            req.prev = kv.last_token          # fresh admit
        self._active.append(req)
        self._dirty = True
        self.admitted << 1
        return None

    # fablint: lock-held(_cv)
    def _interactive_waiting_locked(self) -> bool:
        mx = self.options.interactive_priority_max
        return any(self._pending[b] for b in range(mx + 1))

    # fablint: lock-held(_cv)
    def _pop_interactive_locked(self, now, refusals):
        mx = self.options.interactive_priority_max
        for b in range(mx + 1):
            while self._pending[b]:
                req = self._pending[b].popleft()
                if req.deadline_us is not None \
                        and now >= req.deadline_us:
                    self.expired << 1
                    refusals.append((req, errors.ERPCTIMEDOUT,
                                     "decode deadline expired in batch "
                                     "queue", 0))
                    continue
                return req
        return None

    # fablint: lock-held(_cv)
    def _pick_preempt_locked(self):
        mx = self.options.interactive_priority_max
        best = None
        for req in self._active:
            if req.priority <= mx:
                continue
            if best is None or (req.priority, req.enq_us) > \
                    (best.priority, best.enq_us):
                best = req
        return best

    # ---- the batched step ----------------------------------------------
    def _step_roster(self, roster: List[StepRequest]) -> None:
        bt = self.pool.options.block_tokens
        if self._dirty or self._tbl is None \
                or self._tbl.shape[0] != len(roster):
            self._build_arrays(roster)
            self._dirty = False
        if _flags.get_flag("serving_compiled_step"):
            prev = self._step_compiled(bt)
        else:
            prev = self._step_numpy(bt)
        self._prev = prev
        self._stepi += 1
        toks = prev.tolist()
        for k, req in enumerate(roster):
            req.tokens.append(toks[k])
            req.prev = toks[k]
            req.stepi += 1
        self.tokens_out << len(roster)

    def _build_arrays(self, roster: List[StepRequest]) -> None:
        # r.kv.blocks may be PREFIX-SHARED (ISSUE 16): two rostered
        # sessions with a common prefix gather through the SAME physical
        # block ids — correct by construction (the gather only reads),
        # and the roster pin on each session keeps every shared block's
        # refcount holder alive for the step's lifetime
        maxb = max(len(r.kv.blocks) for r in roster)
        tbl = np.zeros((len(roster), maxb), np.int64)
        for k, r in enumerate(roster):
            tbl[k, :len(r.kv.blocks)] = r.kv.blocks
        self._tbl = tbl
        self._seq = np.array([r.kv.seq_len for r in roster], np.int64)
        self._acc = np.array([r.kv.acc for r in roster], np.int64)
        self._prev = np.array([r.prev for r in roster], np.int64)
        self._stepi = np.array([r.stepi for r in roster], np.int64)
        self._rows = np.arange(len(roster))

    def _step_numpy(self, bt: int) -> np.ndarray:
        """The per-step decode recurrence over the whole roster — one
        gather through the block tables into the pool's reduction arena
        plus elementwise ops (matches the toy model's reference decode
        token for token)."""
        pos = (self._prev + self._stepi) % self._seq
        blk = self._tbl[self._rows, pos // bt]
        read = self.pool.pos_sums_flat[blk * bt + pos % bt]
        return (self._acc + read * (self._stepi + 1)
                + self._prev * 31) % self.options.vocab

    def _step_compiled(self, bt: int) -> np.ndarray:
        """The same step as ONE jit-compiled XLA program, cached per
        (batch-bucket, table-width-bucket) so roster churn compiles a
        handful of programs, not one per shape."""
        import jax
        import jax.numpy as jnp
        b = len(self._rows)
        bpad = 1 << max(b - 1, 0).bit_length()
        wpad = 1 << max(self._tbl.shape[1] - 1, 0).bit_length()
        key = (bpad, wpad, bt)
        fn = self._jit_cache.get(key)
        if fn is None:
            vocab = self.options.vocab

            def _step(pos_flat, tbl, seq, acc, prev, stepi):
                pos = (prev + stepi) % seq
                blk = jnp.take_along_axis(
                    tbl, (pos // bt)[:, None], axis=1)[:, 0]
                read = pos_flat[blk * bt + pos % bt]
                return (acc + read * (stepi + 1) + prev * 31) % vocab

            fn = self._jit_cache[key] = jax.jit(_step)

        def pad(a, n, fill=0):
            out = np.full((n,) + a.shape[1:], fill, a.dtype)
            out[:a.shape[0]] = a
            return out

        tblp = pad(self._tbl, bpad)
        if tblp.shape[1] < wpad:
            tblp = np.pad(tblp, ((0, 0), (0, wpad - tblp.shape[1])))
        out = fn(self.pool.pos_sums_flat, tblp,
                 pad(self._seq, bpad, 1), pad(self._acc, bpad),
                 pad(self._prev, bpad), pad(self._stepi, bpad))
        return np.asarray(out)[:b].astype(np.int64)

    # ---- completion plumbing -------------------------------------------
    def _safe_emit(self, req: StepRequest) -> None:
        try:
            req.emit(req.tokens)
        except Exception:
            from ..butil import logging as log
            log.error("serving: emit for session %s failed",
                      req.session, exc_info=True)

    def _safe_fail(self, req: StepRequest, code: int, text: str,
                   retry_after_ms: int) -> None:
        try:
            req.fail(code, text, retry_after_ms)
        except Exception:
            from ..butil import logging as log
            log.error("serving: fail for session %s failed",
                      req.session, exc_info=True)

    # ---- lifecycle / observability --------------------------------------
    def stop(self) -> None:
        """Fail everything queued/active and join the step thread."""
        with self._cv:
            self._stopping = True
            t = self._thread
            self._thread = None
            self._cv.notify_all()
        if t is not None and t is not threading.current_thread():
            t.join(5.0)
        else:
            # no loop thread (manual mode): drain here
            with self._cv:
                victims = self._drain_locked()
            for req, (code, text) in victims:
                self._safe_fail(req, code, text, 0)

    def owns(self, session: str) -> bool:
        """True while this scheduler holds the session (pending or
        rostered) — the migration fence: a session mid-decode must not
        cut over under its running batched step (ISSUE 19)."""
        with self._cv:
            return session in self._owned

    def queued(self) -> int:
        with self._cv:
            return sum(len(b) for b in self._pending)

    def active(self) -> int:
        with self._cv:
            return len(self._active)

    def step_rate(self) -> float:
        with self._rate_lock:
            return self._rate_ema

    def describe(self) -> dict:
        """The /status serving block's scheduler half."""
        with self._cv:
            active = len(self._active)
            pending = [len(b) for b in self._pending]
        return {
            "active": active,
            "pending_by_band": pending,
            "max_batch": self.options.max_batch,
            "steps": self.steps.get_value(),
            "step_rate_hz": round(self.step_rate(), 1),
            "tokens": self.tokens_out.get_value(),
            "batch_occupancy_avg": round(self.occupancy.average(), 2),
            "admitted": self.admitted.get_value(),
            "retired": self.retired.get_value(),
            "preempted": self.preempted.get_value(),
            "deadline_expired": self.expired.get_value(),
            "rejected": self.rejected.get_value(),
            "compiled_step": bool(
                _flags.get_flag("serving_compiled_step")),
        }
