"""Load-threshold autoscaler: elastic pod membership under traffic.

The serving subsystem's capacity loop (ROADMAP item 3's "ELASTIC
membership" half).  A sampler thread reads one scalar load signal
(typically the decode scheduler's roster+queue pressure, or an
aggregate over pod members' published loads), and after
``samples_to_scale`` CONSECUTIVE samples beyond a watermark — with a
cooldown between actions, so one burst never see-saws the pod — fires
the operator-supplied ``scale_up`` / ``scale_down`` callback.  The
callbacks do the actual work (start a decode worker on a fresh device
and let the Server→Pod advertise hook bump the epoch; lame-duck drain
and stop one for scale-down) so the policy here stays mechanism-free.

Attached to a ``Pod`` (``pod.attach_autoscaler``), the autoscaler also
publishes the sampled load into the local member record each tick
(``Pod.publish_load`` — no epoch bump, load is telemetry not
membership) and appears in the pod's ``/ici`` describe block.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from .. import bvar
from ..butil import debug_sync as _dbg


@dataclass
class AutoscalerOptions:
    high_water: float = 0.75         # load above this long enough → up
    low_water: float = 0.25          # load below this long enough → down
    interval_s: float = 0.5          # sample period
    samples_to_scale: int = 2        # consecutive samples past a mark
    cooldown_s: float = 2.0          # min gap between actions
    min_size: int = 1
    max_size: int = 4


class LoadThresholdAutoscaler:
    """Sample → hysteresis → scale callback.  One per serving pod
    member (usually the one hosting the router)."""

    _GUARDED_BY = {
        "_hi_run": "_lock",
        "_lo_run": "_lock",
        "_last_action_ts": "_lock",
        "_last": "_lock",
        "_running": "_lock",
    }

    def __init__(self, load_fn: Callable[[], float],
                 size_fn: Callable[[], int],
                 scale_up: Callable[[], bool],
                 scale_down: Callable[[], bool],
                 options: Optional[AutoscalerOptions] = None,
                 pod=None,
                 drain: Optional[Callable[[], None]] = None):
        """``drain`` (ISSUE 19): invoked before every ``scale_down``
        so the operator rebalances the doomed worker's live sessions
        first — migrate them to a surviving worker (or spill them to
        the host tier) instead of letting the kill turn them into
        re-prefills.  A raising drain is logged and the scale-down
        still proceeds (capacity policy outranks a failing drain)."""
        self.options = options or AutoscalerOptions()
        self._load_fn = load_fn
        self._size_fn = size_fn
        self._scale_up = scale_up
        self._scale_down = scale_down
        self._drain = drain
        self._pod = pod
        self._lock = _dbg.make_lock("LoadThresholdAutoscaler._lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._hi_run = 0
        self._lo_run = 0
        # "never acted": the cooldown must not gate the FIRST action
        self._last_action_ts = float("-inf")
        self._last: dict = {"load": -1.0, "action": "", "reason": ""}
        self.samples = bvar.Adder("serving_autoscaler_samples")
        self.scale_ups = bvar.Adder("serving_autoscaler_scale_ups")
        self.scale_downs = bvar.Adder("serving_autoscaler_scale_downs")
        if pod is not None:
            pod.attach_autoscaler(self)

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
            self._stop.clear()
            # fablint: thread-quiesced(stop() sets the event and joins; the sample loop checks it every interval)
            t = threading.Thread(target=self._loop,
                                 name="serving_autoscaler", daemon=True)
            self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
            self._thread = None
            self._running = False
        if t is not None and t is not threading.current_thread():
            t.join(2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.options.interval_s):
            try:
                self.tick()
            except Exception:
                from ..butil import logging as log
                log.error("autoscaler tick failed", exc_info=True)

    # ---- the decision ---------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One sample + decision.  Public so tests (and simulated-clock
        harnesses) can drive it without the thread.  Returns "up" /
        "down" when an action fired, else None."""
        o = self.options
        now = time.monotonic() if now is None else now
        load = float(self._load_fn())
        size = int(self._size_fn())
        self.samples << 1
        if self._pod is not None:
            try:
                self._pod.publish_load(load)
            except Exception:
                pass
        action = None
        fire = None
        with self._lock:
            self._last["load"] = round(load, 3)
            if load >= o.high_water:
                self._hi_run += 1
                self._lo_run = 0
            elif load <= o.low_water:
                self._lo_run += 1
                self._hi_run = 0
            else:
                self._hi_run = self._lo_run = 0
            cool = now - self._last_action_ts >= o.cooldown_s
            if (self._hi_run >= o.samples_to_scale and cool
                    and size < o.max_size):
                action, fire = "up", self._scale_up
                reason = (f"load {load:.2f} >= {o.high_water} for "
                          f"{self._hi_run} samples")
            elif (self._lo_run >= o.samples_to_scale and cool
                    and size > o.min_size):
                action, fire = "down", self._scale_down
                reason = (f"load {load:.2f} <= {o.low_water} for "
                          f"{self._lo_run} samples")
            if action is not None:
                self._last_action_ts = now
                self._hi_run = self._lo_run = 0
                self._last["action"] = action
                self._last["reason"] = reason
        if fire is None:
            return None
        if action == "down" and self._drain is not None:
            try:
                self._drain()
            except Exception:
                from ..butil import logging as log
                log.error("autoscaler drain before scale_down failed",
                          exc_info=True)
        ok = False
        try:
            ok = bool(fire())
        except Exception:
            from ..butil import logging as log
            log.error("autoscaler scale_%s failed", action, exc_info=True)
        if ok:
            (self.scale_ups if action == "up" else self.scale_downs) << 1
        return action if ok else None

    # ---- observability --------------------------------------------------
    def describe(self) -> dict:
        o = self.options
        with self._lock:
            last = dict(self._last)
            running = self._running
        return {
            "running": running,
            "high_water": o.high_water,
            "low_water": o.low_water,
            "interval_s": o.interval_s,
            "size": self._size_fn(),
            "min_size": o.min_size,
            "max_size": o.max_size,
            "samples": self.samples.get_value(),
            "scale_ups": self.scale_ups.get_value(),
            "scale_downs": self.scale_downs.get_value(),
            "last": last,
        }
