"""Zero-copy KV handoff sources (ISSUE 15): the prefill attachment's
bytes land DIRECTLY in :class:`~brpc_tpu.serving.PagedKvPool` blocks.

The PR-14 loader paid one full host-side materialization per session at
the pool boundary: ``attachment.to_bytes()`` (copy 1) → the layer-major
→ token-major transpose reshape (copy 2) → the pool's block fill
(copy 3).  For a 1536-token session LoadKv was the single largest
byte-moving operation left on the host, and it runs once per
prefill→decode handoff AND once per re-prefill retry around a kill.

Here the wire segments are wrapped as read-only views and scattered
STRAIGHT into the block views ``PagedKvPool.load_into`` reserves —
every payload byte is copied exactly once, whatever the plane:

  * **adopted** — host-byte segments consumed in place: the shm ring
    claim (a USER block wrapping the ring slot itself — PR 10's
    consume-to-release credit is the custody model: the slot retires
    when the consumed claim's last ref dies, which the loader forces
    right after the fill) and plain HOST/bulk-claim blocks;
  * **scattered** — device segments: a parked ``NativeAttachment``
    handle's segs are TAKEN raw (:meth:`NativeAttachment.take_segments`
    — no IOBuf inflation, the PR-12 exactly-one-exit custody holds) and
    loopback/device blocks viewed via ``np.asarray``, then scattered
    block-wise.  Segment boundaries need not align with pool block (or
    token, or layer) boundaries — the scatter loop handles straddling;
  * **materialized** — the PR-14 fallback, kept byte-for-byte behind
    ``serving_kv_adopt=False`` for same-run A/B.

Per-route truth rides ``serving_kv_load_{adopted,scattered,
materialized}`` Adders plus ``serving_kv_load_copy_bytes`` (host copy
PASSES × payload bytes: ≤1× on the adopted/scattered routes, 3× on the
materialized one), snapshot via :func:`kv_load_stats` — the /status
serving block and the tests' route assertions read exactly this.
"""
from __future__ import annotations

import bisect
from typing import Dict, List, Optional

import numpy as np

from .. import bvar
from ..butil import debug_sync as _dbg
from ..butil import flags as _flags
from ..butil.iobuf import DEVICE, IOBuf
from ..ici import route as _route

_flags.define_flag(
    "serving_kv_adopt", True,
    "land prefill->decode KV attachment bytes directly in PagedKvPool "
    "blocks (shm claims consumed in place, native att segments taken "
    "block-wise; one copy pass).  False restores the PR-14 "
    "materialize-then-load path byte-for-byte for same-run A/B")

ADOPTED = "adopted"
SCATTERED = "scattered"
MATERIALIZED = "materialized"


def adopt_enabled() -> bool:
    return bool(_flags.get_flag("serving_kv_adopt"))


class _KvLoadStats:
    """Route-assertion surface for every KV load in the process: which
    path carried each session's bytes and how many host copy passes
    they paid.  Adders are write-local; the per-route byte ledger is
    the guarded half."""

    _GUARDED_BY = {"_route_bytes": "_lock"}

    def __init__(self):
        self._lock = _dbg.make_lock("kv_source._KvLoadStats._lock")
        self._route_bytes: Dict[str, int] = {}
        self.adopted = bvar.Adder("serving_kv_load_adopted")
        self.scattered = bvar.Adder("serving_kv_load_scattered")
        self.materialized = bvar.Adder("serving_kv_load_materialized")
        self.copy_bytes = bvar.Adder("serving_kv_load_copy_bytes")

    def record(self, route: str, payload_bytes: int,
               copy_passes: int) -> None:
        {ADOPTED: self.adopted, SCATTERED: self.scattered,
         MATERIALIZED: self.materialized}[route] << 1
        self.copy_bytes << payload_bytes * copy_passes
        with self._lock:
            self._route_bytes[route] = \
                self._route_bytes.get(route, 0) + payload_bytes

    def snapshot(self) -> dict:
        with self._lock:
            by_route = dict(self._route_bytes)
        return {
            "adopted": self.adopted.get_value(),
            "scattered": self.scattered.get_value(),
            "materialized": self.materialized.get_value(),
            "copy_bytes": self.copy_bytes.get_value(),
            "payload_bytes_by_route": by_route,
        }


stats = _KvLoadStats()


def kv_load_stats() -> dict:
    """{route: loads, copy_bytes, payload_bytes_by_route} — the /status
    serving block's ``kv_load`` field, rpc_press's serving summary, and
    the bench/tests' per-call route assertion."""
    return stats.snapshot()


def _write_flat(dest: np.ndarray, off: int, chunk: np.ndarray) -> None:
    """Write a contiguous 1-D ``chunk`` into the strided 2-D ``dest``
    starting at row-major flat offset ``off`` — the straddle primitive:
    head partial row, vectorized middle, tail partial row."""
    ncols = dest.shape[1]
    n = chunk.shape[0]
    i = 0
    r, c = divmod(off, ncols)
    if c:
        take = min(ncols - c, n)
        dest[r, c:c + take] = chunk[:take]
        i = take
        r += 1
    full = (n - i) // ncols
    if full:
        dest[r:r + full] = chunk[i:i + full * ncols].reshape(full, ncols)
        i += full * ncols
        r += full
    if i < n:
        dest[r, :n - i] = chunk[i:]


class WireKvSource:
    """One LoadKv payload as ordered read-only uint8 views over the wire
    segments, plus the ``fill`` that scatters the layer-major wire
    layout ``(layers, seq_len, dmodel)`` into the pool's token-major
    block views — each payload byte read once, written once.

    The dominant single-segment shape (one device array / one ring
    claim) runs ONE strided transpose-assignment per pool block; the
    general shape walks (block × layer) destination slices through the
    segment list, splitting at segment boundaries wherever they fall
    (mid-block, mid-token, even mid-layer-row).  Instances are
    single-use: ``fill`` once, then the loader drops the object so
    claim credit / array refs release deterministically.  A ``fill``
    after :meth:`release` raises loudly — since ISSUE 16 the pool runs
    fills OUTSIDE its lock, so a stale callback invoked late must fail
    typed instead of scattering zero segments and publishing a table
    over stale arena bytes."""

    __slots__ = ("route", "layers", "seq_len", "dmodel", "_segs",
                 "_starts")

    def __init__(self, segments: List[np.ndarray], route: str,
                 layers: int, seq_len: int, dmodel: int):
        self.route = route
        self.layers = layers
        self.seq_len = seq_len
        self.dmodel = dmodel
        self._segs = segments
        starts = [0]
        for s in segments:
            starts.append(starts[-1] + s.shape[0])
        self._starts = starts

    @property
    def total(self) -> int:
        return self._starts[-1]

    def fill(self, views: List[np.ndarray]) -> None:
        """The ``PagedKvPool.load_into`` fill callback (may run outside
        the pool lock; it only writes the reserved views)."""
        if not self._segs:
            raise RuntimeError(
                "WireKvSource.fill after release(): sources are "
                "single-use — build a fresh source per load")
        L, D = self.layers, self.dmodel
        if len(self._segs) == 1:
            wire = self._segs[0].reshape(L, self.seq_len, D)
            t0 = 0
            for v in views:
                n = v.shape[0]
                # one strided copy per block: wire (L, n, D) slab →
                # token-major (n, L, D) rows, transposed in-assignment
                v.reshape(n, L, D)[...] = \
                    wire[:, t0:t0 + n, :].transpose(1, 0, 2)
                t0 += n
            return
        t0 = 0
        for v in views:
            n = v.shape[0]
            for layer in range(L):
                self._copy_rows(layer, t0, n,
                                v[:, layer * D:(layer + 1) * D])
            t0 += n

    def _copy_rows(self, layer: int, t0: int, n: int,
                   dest: np.ndarray) -> None:
        """Copy layer ``layer``'s bytes for tokens [t0, t0+n) into the
        strided dest (n, dmodel) view, walking the segment list."""
        D = self.dmodel
        pos = (layer * self.seq_len + t0) * D
        need = n * D
        i = bisect.bisect_right(self._starts, pos) - 1
        off = 0
        while need > 0:
            seg = self._segs[i]
            a = pos + off - self._starts[i]
            take = min(seg.shape[0] - a, need)
            _write_flat(dest, off, seg[a:a + take])
            off += take
            need -= take
            i += 1

    def release(self) -> None:
        """Drop the segment views NOW: the shm ring claim's
        consume-to-release credit returns (and taken device arrays
        free) at a deterministic point instead of a later GC."""
        self._segs = []
        self._starts = [0]


def _load_route(sock, cls: str, nbytes: int) -> str:
    """Adopt-vs-scatter through the SHARED route table (ISSUE 17) —
    the payload class here is the same HOST/DEVICE split that orders
    ``route.candidates()``, not a private kind ladder:

      * DEVICE-class bytes always scatter (the D2H crossing is the
        wire transfer itself, never a host copy pass);
      * HOST-class bytes adopt in place, UNLESS the carrying socket is
        known and its plane-health records say every descriptor plane
        (shm, bulk) has left UP — then the load is recorded SCATTERED,
        so the route-assertion surface never claims an in-place
        adoption rode a healthy plane it didn't.  Custody is safe on
        both labels (a retired ring keeps claimed slots alive until
        the last ref dies); what the consultation changes is that the
        counters tell the truth about plane state at load time.
    """
    if cls == _route.DEVICE:
        return SCATTERED
    if sock is not None and _route.SHM not in (
            planes := _route.candidates(sock, _route.HOST, nbytes)) \
            and _route.BULK not in planes:
        return SCATTERED
    return ADOPTED


def wire_source(att: IOBuf, layers: int, seq_len: int,
                dmodel: int, sock=None) -> WireKvSource:
    """Build the scatter source for one LoadKv attachment.  The VIEW
    mechanics stay per-block (custody is what the attachment is); the
    adopt-vs-scatter ROUTE comes from :func:`_load_route`, which asks
    ``route.candidates()`` / plane-health when ``sock`` (the fabric
    socket that carried the request) is supplied:

      * an untouched parked ``NativeAttachment`` → ``take_segments()``
        (the custody exit that never builds IOBuf blocks), DEVICE
        class;
      * a plain IOBuf → zero-copy views per backing block: HOST/USER
        blocks (shm ring claims, bulk claims, inline bytes) viewed via
        ``np.frombuffer``; DEVICE blocks (loopback / an
        already-materialized native view) via ``np.asarray`` (the D2H
        crossing is the wire transfer itself, not a host copy pass).
    """
    take = getattr(att, "take_segments", None)
    if take is not None and att.parked:
        segs = []
        # arrays re-emerging from native custody are FLAT UINT8 by
        # construction — append_device_array validates shape/dtype at
        # entry and the unchecked path only re-posts registry arrays
        # that entered through it — so element counts ARE byte counts
        for arr, nbytes in take():
            view = np.asarray(arr)
            if view.shape[0] != nbytes:
                view = view[:nbytes]
            segs.append(view)
        return WireKvSource(
            segs, _load_route(sock, _route.DEVICE, len(att)),
            layers, seq_len, dmodel)
    segs = []
    dev = False
    for i in range(att.backing_block_num()):
        r = att.backing_block(i)
        b = r.block
        if b.kind == DEVICE:
            # DEVICE blocks are flat uint8 (enforced at
            # append_device_array), so ref offset/length index bytes
            dev = True
            if r.offset == 0 and r.length == b.size:
                # whole-block (the dominant shape): asarray the array
                # itself so repeated sends hit jax's cached host value
                seg = np.asarray(b.data)
            else:
                # partial ref (IOBuf cut ops move refs, never bytes):
                # slice ON DEVICE first so only the referenced bytes
                # pay the D2H crossing, not the whole backing array
                seg = np.asarray(b.data[r.offset:r.offset + r.length])
        else:
            seg = np.frombuffer(b.data, np.uint8)[
                r.offset:r.offset + r.length]
        segs.append(seg)
    return WireKvSource(
        segs,
        _load_route(sock, _route.DEVICE if dev else _route.HOST,
                    len(att)),
        layers, seq_len, dmodel)


def load_wire_attachment(pool, att: IOBuf, session: str, seq_len: int,
                         layers: int, dmodel: int, *, last_token: int,
                         tenant: str = "",
                         priority: Optional[int] = None,
                         sock=None):
    """The whole zero-copy handoff in one call: build the source, let
    the pool reserve-and-fill (outside the pool lock by default since
    ISSUE 16, so concurrent LoadKv scatters proceed in parallel),
    record the route, and release the segment views (ring credit back,
    device refs dropped) whether the load committed or aborted.  Pool
    refusals (PoolSaturated / SessionBusy — the latter now also fired
    by the commit-time re-check when a raced loader's entry got
    pinned mid-fill) propagate for the RPC layer's shed mapping."""
    src = wire_source(att, layers, seq_len, dmodel, sock=sock)
    try:
        want = seq_len * layers * dmodel
        if src.total != want:
            raise ValueError(
                f"kv wire segments hold {src.total} bytes, "
                f"descriptor said {want}")
        s = pool.load_into(session, seq_len, src.fill,
                           last_token=last_token, tenant=tenant,
                           priority=priority)
    finally:
        src.release()
    stats.record(src.route, seq_len * layers * dmodel, 1)
    return s


def load_token_major_attachment(pool, att: IOBuf, session: str,
                                seq_len: int, *, last_token: int,
                                tenant: str = "",
                                priority: Optional[int] = None,
                                sock=None):
    """The KV MIGRATION ingest (ISSUE 19): the payload is already
    token-major ``(seq_len, bytes_per_token)`` — a pool-to-pool
    transfer ships the source pool's row layout verbatim, so there is
    no layer transpose to undo.  Declaring ``layers=1`` with
    ``dmodel=bytes_per_token`` makes the wire layout identical to the
    pool's block rows and the scatter one strided copy per extent;
    everything else (route accounting, segment custody, the pool's
    reserve/fill-outside-the-lock/commit with SessionBusy/saturation
    sheds) is byte-for-byte :func:`load_wire_attachment`."""
    return load_wire_attachment(
        pool, att, session, seq_len, 1, pool.options.bytes_per_token,
        last_token=last_token, tenant=tenant, priority=priority,
        sock=sock)
