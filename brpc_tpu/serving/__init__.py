"""brpc_tpu.serving — the production serving subsystem (ROADMAP item 3).

Four pieces, each usable alone, composed by the disaggregated-serving
workers (``examples/disagg_serving`` is built ON this package):

  * :mod:`.kv_pool` — ``PagedKvPool``: fixed-size device blocks, a free
    list, per-session block tables, admission-aware eviction (the PR-9
    tenant/priority policy decides who absorbs memory pressure), a
    TimerThread-driven expiry sweep (idle workers reclaim parked KV
    with zero traffic), and — since ISSUE 16 — copy-on-write PREFIX
    SHARING (sessions with a block-aligned common prefix map the same
    refcounted physical blocks; ``write_rows`` CoW-splits on mutation)
    plus OUTSIDE-THE-LOCK fills (``load_into`` reserves under the pool
    lock, scatters unlocked, commits with a re-check — concurrent
    LoadKv fills no longer serialize);
  * :mod:`.scheduler` — ``ContinuousBatchScheduler``: one batched
    decode step per tick over the active session set, sessions
    admitted/retired/preempted BETWEEN steps;
  * :mod:`.kv_source` — the zero-copy KV handoff (ISSUE 15): wire
    attachment segments (shm ring claims, parked native att handles,
    loopback device blocks) scatter DIRECTLY into the pool blocks
    ``load_into`` reserves — one copy pass, route-asserted via
    ``serving_kv_load_*`` counters;
  * :mod:`.router` — ``LoadAwareRouter``: prefill→decode routing by
    load through the LALB divided-weight balancer, with elastic
    membership from a naming url (``pod://``) and — since ISSUE 19 —
    session AFFINITY (``bind_session``/``rebind``: the migration
    cutover is one atomic affinity flip);
  * :mod:`.migration` — live cross-worker KV migration (ISSUE 19):
    ``migrate_out`` ships a pinned session's blocks to another pool
    under a transfer-deadline plane-health latch, source authoritative
    until the destination commits;
  * :mod:`.autoscaler` — ``LoadThresholdAutoscaler``: the elastic-pod
    capacity loop (watermarks + hysteresis + cooldown → scale
    callbacks; Server→Pod advertise/withdraw hooks move the epoch).
"""
from .autoscaler import AutoscalerOptions, LoadThresholdAutoscaler
from .kv_pool import (KvPoolOptions, PagedKvPool, PoolSaturated,
                      SessionBusy)
from .kv_source import (WireKvSource, kv_load_stats,
                        load_token_major_attachment,
                        load_wire_attachment, wire_source)
from .migration import migrate_out, migration_stats
from .router import LoadAwareRouter
from .scheduler import (BatchSchedulerOptions, ContinuousBatchScheduler,
                        StepRequest)

__all__ = [
    "AutoscalerOptions",
    "BatchSchedulerOptions",
    "ContinuousBatchScheduler",
    "KvPoolOptions",
    "LoadAwareRouter",
    "LoadThresholdAutoscaler",
    "PagedKvPool",
    "PoolSaturated",
    "SessionBusy",
    "StepRequest",
    "WireKvSource",
    "kv_load_stats",
    "load_token_major_attachment",
    "load_wire_attachment",
    "migrate_out",
    "migration_stats",
    "wire_source",
]
