"""Live cross-worker KV migration (ISSUE 19, ROADMAP 2c): pool-to-pool
block transfer so the router/autoscaler REBALANCE a live session onto
another decode worker instead of re-prefilling around a kill.

The custody story is deliberately conservative — the SOURCE copy stays
authoritative until the destination has COMMITTED:

  * :func:`migrate_out` PINS the source session (restoring it from the
    host tier first if it was spilled — a migration is a read), takes
    an atomic snapshot, and copies the payload bytes UP FRONT.  The
    copy is what makes the deadline latch safe: a hung ``send`` thread
    abandoned past the deadline holds its own bytes, so it can never
    ship arena rows that were freed and reused after the abort.  On a
    TPU pod the block transfer itself is the pallas
    ``make_async_remote_copy`` device-plane DMA (SNIPPETS [2]); this
    host-staged copy is the portable shape and the honest residue.
  * ``send(meta, payload) -> (ok, err, shed)`` is the caller's wire
    (the disagg example drives ``Decode.MigrateIn`` on the destination,
    which loads the token-major payload through the pool's ordinary
    reserve/fill-outside-the-lock/commit path).  ``shed=True`` marks a
    CLEAN refusal — destination saturated or the session id busy there
    — which aborts the migration without degrading the plane.
  * the "migrate" plane-health row carries the liveness signal the
    PR-17 residue asked for: a TRANSFER-DEADLINE LATCH.  A send that
    neither completes nor fails within the deadline marks the plane
    down (``transfer_deadline``) and the migration aborts with the
    source intact — a hung peer is detected by the deadline, not by a
    client in the blast radius, and every later ``migrate_out`` refuses
    FAST until the timer latch lapses and the plane revives through the
    standard reprobe/revived/ramp counters.
  * only after the destination commits does the source release: the
    caller's ``on_cutover`` (the atomic routing flip —
    ``LoadAwareRouter.rebind``) runs FIRST, then the source pin drops
    and the blocks free.  A mid-migration kill of either end leaves the
    surviving copy authoritative and the router's PR-14 re-prefill
    retry path covers the gap.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple

from .. import bvar
from ..butil import debug_sync as _dbg
from ..butil import flags as _flags

_flags.define_flag(
    "serving_migrate_deadline_ms", 2000,
    "transfer-deadline latch for live KV migration: a send that "
    "neither completes nor fails within this window marks the migrate "
    "plane down and the migration aborts with the source copy intact")

_flags.define_flag(
    "serving_migrate_reprobe_s", 0.5,
    "migrate plane-health timer latch: how long after a transfer "
    "deadline / peer failure before the next migrate_out re-probes "
    "the plane optimistically")


class _MigrationStats:
    """Process-wide migration ledger: the /status serving ``tiers``
    block's ``migration`` half and the chaos tests' assertion surface.
    Adders are write-local; the last-abort diagnostic is the guarded
    half."""

    _GUARDED_BY = {"_last_abort": "_lock"}

    def __init__(self):
        self._lock = _dbg.make_lock("migration._MigrationStats._lock")
        self._last_abort = ""
        self.migrations_out = bvar.Adder("serving_kv_migrations_out")
        self.migrations_in = bvar.Adder("serving_kv_migrations_in")
        self.cutovers = bvar.Adder("serving_kv_migration_cutovers")
        self.aborts = bvar.Adder("serving_kv_migration_aborts")
        self.bytes_moved = bvar.Adder("serving_kv_migration_bytes")

    def abort(self, reason: str) -> None:
        self.aborts << 1
        with self._lock:
            self._last_abort = reason

    def snapshot(self) -> dict:
        with self._lock:
            last = self._last_abort
        return {
            "migrations_out": self.migrations_out.get_value(),
            "migrations_in": self.migrations_in.get_value(),
            "cutovers": self.cutovers.get_value(),
            "aborts": self.aborts.get_value(),
            "bytes_moved": self.bytes_moved.get_value(),
            "last_abort": last,
        }


stats = _MigrationStats()

# fablint custody contract (ISSUE 20): the source pin taken by
# migrate_out must drop on EVERY exit — abort, deadline latch, shed,
# and the cutover success path all funnel through the one finally.
_CUSTODY = {
    "pin": ("unpin",),
}

_health = None
_health_lock = _dbg.make_lock("migration._health_lock")


def migrate_health():
    """The process-wide "migrate" plane-health row (timer-latch
    policy), created lazily so a process that never migrates never
    registers the plane."""
    global _health
    with _health_lock:
        if _health is None:
            from ..ici.plane_health import register_plane
            _health = register_plane(
                "migrate",
                retry_s=lambda: float(_flags.get_flag(
                    "serving_migrate_reprobe_s")))
        return _health


def migration_stats() -> dict:
    """Ledger + plane row — ``describe()['tiers']['migration']``,
    rpc_press's serving summary, and the chaos assertions read this."""
    out = stats.snapshot()
    with _health_lock:
        h = _health
    if h is not None:
        out["plane"] = h.snapshot()
    return out


def migrate_out(pool, session: str,
                send: Callable[[dict, bytes],
                               Tuple[bool, str, bool]], *,
                scheduler=None,
                on_cutover: Optional[Callable[[], None]] = None,
                deadline_ms: Optional[int] = None) -> Tuple[bool, str]:
    """Move one session's KV to another worker's pool.  Returns
    ``(ok, reason)`` — every failure leaves the SOURCE copy
    authoritative and serving.

    ``send(meta, payload)`` ships the token-major payload to the
    destination and returns ``(ok, err, shed)``; it runs on its own
    thread under the transfer-deadline latch.  ``scheduler`` (when
    given) fences sessions the decode roster owns — migrating a
    session mid-decode would cut over under a running batched step.
    ``on_cutover`` is the atomic routing flip, invoked after the
    destination committed and BEFORE the source releases."""
    health = migrate_health()
    if not health.usable():
        # the plane is latched down (hung peer / dead transfer): refuse
        # fast, no client in the blast radius
        stats.abort("plane down")
        return False, "migrate plane down (latched): retry later"
    if scheduler is not None and scheduler.owns(session):
        stats.abort("session decoding")
        return False, f"session {session!r} is decoding: drain first"
    if not pool.pin(session):
        stats.abort("unknown session")
        return False, f"unknown session {session!r}"
    try:
        snap = pool.snapshot(session)
        s = pool.get(session)
        if snap is None or s is None:
            stats.abort("session vanished")
            return False, f"session {session!r} vanished under the pin"
        rows, seq_len, last_token = snap
        meta = {"session": session, "seq_len": int(seq_len),
                "last_token": int(last_token), "tenant": s.tenant,
                "priority": int(s.priority)}
        # the up-front copy: after this line the send thread owns its
        # own bytes — an abandoned (deadline-latched) sender can never
        # read arena rows the abort path freed for reuse
        payload = rows.tobytes()
        result = {}
        done = threading.Event()

        def _runner():
            try:
                result["r"] = send(meta, payload)
            except Exception as e:   # a raising send is a dead peer
                result["r"] = (False, f"{type(e).__name__}: {e}", False)
            finally:
                done.set()

        dl_ms = deadline_ms if deadline_ms is not None else int(
            _flags.get_flag("serving_migrate_deadline_ms"))
        # fablint: thread-quiesced(daemon sender owns a private payload copy; abandoned past the deadline it can only set an Event nobody waits on)
        threading.Thread(target=_runner, name="kv_migrate_send",
                         daemon=True).start()
        if not done.wait(dl_ms / 1000.0):
            # the PR-17 residue fix: a hung peer is DETECTED here, by
            # the transfer deadline, and latches the plane down — not
            # by some later client timing out into the blast radius
            health.mark_down("transfer_deadline")
            stats.abort("transfer deadline")
            return False, (f"transfer exceeded {dl_ms}ms deadline: "
                           "migrate plane latched down")
        ok, err, shed = result["r"]
        if not ok:
            if not shed:
                # transport-level failure (dead socket, refused
                # connection): the peer, not the payload, is the
                # problem — latch the plane
                health.mark_down("peer_unreachable")
            stats.abort(err or "send failed")
            return False, err or "send failed"
        # destination committed: cut over, then (and only then) let
        # the source copy go
        stats.migrations_out << 1
        stats.bytes_moved << len(payload)
        if on_cutover is not None:
            on_cutover()
        stats.cutovers << 1
    finally:
        pool.unpin(session)
    pool.release(session)
    return True, ""
