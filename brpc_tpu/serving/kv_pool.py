"""Paged KV-block pool: fixed-size device blocks, free-list custody,
per-session block tables, admission-aware eviction, timer-driven expiry,
copy-on-write prefix sharing, host-tier spill/restore.

The serving subsystem's memory manager (ROADMAP item 3; the shape every
production LLM server converged on — vLLM's PagedAttention block tables
over a fixed block pool).  One pool per decode worker:

  * **Blocks, not sessions, are the allocation unit.**  The backing
    store is a fixed ``(num_blocks, block_tokens × bytes_per_token)``
    uint8 arena plus a parallel ``(num_blocks, block_tokens)`` int64
    per-token reduction arena (the "attention read" surface the batched
    decode step gathers from — one fancy-index gather per step through
    the block tables, never a per-session copy).  A session holds an
    ordered block list; fragmentation is impossible by construction.
  * **Copy-on-write prefix sharing** (ISSUE 16): at commit time FULL
    blocks are content-hashed (a chained CRC over the block run, so the
    key encodes position-in-prefix) against a pool-wide prefix index —
    when N sessions' token rows share a block-aligned prefix they map
    the SAME physical blocks under a per-block REFCOUNT (the block-level
    analog of the counted session pin: a shared block outlives any one
    owner and frees only when the last refcount drops).  Every index
    hit is BYTE-VERIFIED before sharing, so a hash collision degrades
    to no-sharing, never to cross-session bytes.  Divergence past the
    common prefix keeps private tail blocks, and an in-place
    ``write_rows`` on a shared block performs a CoW SPLIT to a private
    copy first.  ``serving_kv_prefix_share=False`` restores the PR-15
    private-blocks world byte-for-byte for same-run A/B.
  * **Admission-aware eviction** (the PR-9 integration): under memory
    pressure the pool evicts parked sessions in PRIORITY-BAND order —
    sheddable/batch bands (higher band number) before interactive ones,
    lighter admission tenant weights before heavier ones inside a band,
    LRU inside a (band, weight) class — and a loading session may NEVER
    evict a session from a band more protected than its own.  Tenant
    weights come from the same ``AdmissionOptions.tenant_weight``
    table the WFQ admission queue uses (``KvPoolOptions.from_admission``),
    so "who absorbs the pressure" is ONE policy across queueing and
    memory.  Victim selection simulates the refcount decrements, so a
    victim whose blocks other sessions still share contributes only the
    blocks that would actually free.
  * **Timer-driven expiry**, not traffic-driven (the ISSUE-14 bugfix):
    the old example swept stale sessions only inside ``LoadKv``, so an
    idle decode worker parked expired KV forever.  Here the sweep is a
    TimerThread callback scheduled whenever sessions exist — a parked
    session on an otherwise-idle worker is reclaimed on time with zero
    new traffic.  The timer is scheduled lazily (first load) and
    self-cancels when the pool drains, so an idle pool costs nothing.
  * **Pins** fence eviction: the decode scheduler pins every session in
    its step roster; pinned sessions are never evicted or expired (their
    block tables are live in the current batched program).

Custody: a session's bytes enter the pool exactly once and leave by
exactly one of release / evict / expire / close — where "leave" for a
SHARED block means its refcount decrement, the physical free happening
only at zero.  Two entry surfaces:

  * ``load`` — the caller already holds the whole session as one
    contiguous token-major array (the PR-14 materialized path, kept for
    A/B and for sources that cannot scatter).  Since ISSUE 16 it is a
    thin delegation to ``load_into`` with a row-copy fill, so both
    surfaces ride ONE reserve/fill/commit shape (locking parity is
    structural, not duplicated);
  * ``load_into`` (ISSUE 15) — the block table is RESERVED first, then
    the caller's ``fill`` writes token rows DIRECTLY into the arena
    blocks, so a loader never materializes the session as one
    intermediate array.  The serving loader feeds this from the wire:
    shm ring claims and parked native att segments scatter straight
    into the reserved blocks (``serving/kv_source.py``), one copy pass
    total.  Since ISSUE 16 the fill runs OUTSIDE the pool lock by
    default (``serving_kv_concurrent_fill``): reserve under the lock,
    scatter unlocked, COMMIT WITH A RE-CHECK — so concurrent LoadKv
    fills no longer serialize on one decode host.

ISSUE 19 adds the HOST TIER (ROADMAP 2b): with ``host_blocks > 0`` the
victim picker's "evict" becomes "demote" — a pressure victim's blocks
are copied into a host arena (a refcounted shared block spills ONCE)
and the session becomes retrievable instead of dead.  Any later touch
(get / pin / snapshot / write_rows / the scheduler's roster add)
RESTORES it through the same reserve / fill-outside-the-lock / commit
shape ``load_into`` rides, with a chained-CRC byte verification so a
corrupted host block degrades to a typed re-prefill shed, never to
serving wrong bytes.  The spill path registers as a plane-health row
("spill", timer-latch policy) so a failing host arena degrades
in-policy — demotes stop, eviction falls back to the PR-16 behavior —
and revives through the standard reprobe/ramp counters.
"""
from __future__ import annotations

import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from .. import bvar
from ..butil import custody_ledger as _ledger
from ..butil import debug_sync as _dbg
from ..butil import flags as _flags

_flags.define_flag(
    "serving_kv_prefix_share", True,
    "content-hash FULL KV blocks at load commit so sessions sharing a "
    "block-aligned prefix map the same physical blocks under a "
    "refcount (byte-verified on every hit; divergence or write_rows "
    "triggers a CoW split to a private copy).  False restores the "
    "PR-15 private-blocks-per-session behavior byte-for-byte for "
    "same-run A/B")

_flags.define_flag(
    "serving_kv_concurrent_fill", True,
    "run load_into's fill OUTSIDE the pool lock: reserve under the "
    "lock, scatter unlocked, commit with a re-check — concurrent "
    "LoadKv fills proceed in parallel instead of serializing.  False "
    "restores the PR-15 hold-through-the-fill discipline byte-for-byte "
    "for same-run A/B")

_flags.define_flag(
    "serving_kv_spill", True,
    "demote pressure victims to the host arena tier instead of "
    "evicting them (pools built with host_blocks > 0).  False restores "
    "the PR-16 evict-on-pressure behavior byte-for-byte for same-run "
    "A/B — the capacity-under-pressure bench leg flips exactly this")

_flags.define_flag(
    "serving_kv_spill_reprobe_s", 0.25,
    "spill plane-health timer latch: how long after a demote/restore "
    "IO failure before the first use re-probes the host tier "
    "optimistically")


class SessionBusy(RuntimeError):
    """``load`` hit a session id that is PINNED in the step roster: a
    re-prefill while the first decode still runs.  Freeing a rostered
    session's blocks would hand them to the new bytes mid-program (the
    running gather would read the replacement's KV), so the reload is
    refused — the RPC layer maps this to a retryable shed.  The same
    refusal fires at COMMIT time when a concurrent loader won the race
    for the session id and its entry got pinned before our re-check."""

    def __init__(self, session: str):
        super().__init__(
            f"session {session!r} is pinned in the decode roster; "
            f"re-prefill must wait for (or cancel) the running decode")
        self.session = session


class PoolSaturated(RuntimeError):
    """``load`` could not free enough blocks: every candidate session is
    pinned or lives in a band more protected than the requester's.  The
    RPC layer maps this to retryable ``ELIMIT`` + a ``retry_after_ms``
    hint — the shed, not a failure."""

    def __init__(self, needed: int, free: int):
        super().__init__(
            f"kv pool saturated: need {needed} blocks, {free} free and "
            f"no evictable session in an equal-or-less-protected band")
        self.needed = needed
        self.free = free


@dataclass
class KvPoolOptions:
    """Pool geometry + the eviction/expiry policy."""
    bytes_per_token: int
    num_blocks: int = 256
    block_tokens: int = 16
    bands: int = 4                   # priority bands, 0 = most protected
    default_priority: int = 2        # sessions arriving without one
    # host-tier arena size in blocks (ISSUE 19): 0 disables spill —
    # pressure evicts exactly as before
    host_blocks: int = 0
    ttl_s: float = 120.0             # idle-session expiry
    sweep_interval_s: float = 0.0    # 0 = auto: ttl_s / 4, floored
    use_timers: bool = True          # False: tests drive expire_idle()
    tenant_weights: Dict[str, int] = field(default_factory=dict)
    default_tenant_weight: int = 1

    @classmethod
    def from_admission(cls, adm, **kw) -> "KvPoolOptions":
        """Derive the eviction policy from a PR-9 ``AdmissionOptions``
        so queue fairness and memory pressure share one tenant table."""
        kw.setdefault("bands", adm.bands)
        kw.setdefault("default_priority", adm.default_priority)
        kw.setdefault("tenant_weights", dict(adm.tenant_weights))
        kw.setdefault("default_tenant_weight", adm.default_tenant_weight)
        return cls(**kw)

    def effective_sweep_s(self) -> float:
        if self.sweep_interval_s > 0:
            return self.sweep_interval_s
        return max(self.ttl_s / 4.0, 0.05)


class _KvSession:
    """One session's block table (access under the pool lock; the
    numeric fields are immutable after load, so the scheduler may READ
    blocks/seq_len/acc/last_token from its roster snapshot lock-free —
    ``write_rows`` preserves this by publishing a NEW blocks array on a
    CoW split, never mutating the one a roster snapshot may hold).

    ``pinned`` is a COUNT (ISSUE 15), not a flag: the step roster holds
    one pin per roster entry and a zero-copy ``snapshot(view=True)``
    reader holds another — either alone fences eviction/expiry, and
    releasing one must not unfence the other.  ``release_pending``
    marks a ``release`` that arrived while pinned: the free is DEFERRED
    to the last unpin instead of yanking blocks out from under a
    reader (or being silently dropped).  ISSUE 16 extends the same
    counted-holder idea one level down: a PHYSICAL block shared across
    sessions carries a pool-side refcount (``PagedKvPool._refs``) that
    outlives any one owner — a session's free decrements, the block
    only rejoins the free list at zero."""

    __slots__ = ("session", "tenant", "priority", "seq_len", "last_token",
                 "acc", "blocks", "last_used", "pinned",
                 "release_pending", "contiguous")

    def __init__(self, session: str, tenant: str, priority: int,
                 seq_len: int, last_token: int, acc: int,
                 blocks: np.ndarray, now: float):
        self.session = session
        self.tenant = tenant
        self.priority = priority
        self.seq_len = seq_len
        self.last_token = last_token
        self.acc = acc
        self.blocks = blocks             # np.int64 (n_blocks,)
        self.last_used = now
        self.pinned = 0
        self.release_pending = False
        # blocks are immutable after commit, so the one-ascending-
        # extent test is computed ONCE here — snapshot(view=True)'s
        # per-read eligibility is a field read, not an array compare
        # (prefix-share dedupe and CoW splits recompute it when they
        # publish a substituted array)
        self.contiguous = bool((np.diff(blocks) == 1).all())


class _SpilledSession:
    """One session parked in the host tier (access under the pool
    lock).  ``hblocks`` indexes the host arena; ``crcs`` holds the
    CHAINED crc32 per block position, computed from the DEVICE bytes at
    demote time — the restore path recomputes the chain from the host
    copy and any divergence aborts the restore into a typed re-prefill
    shed, never into serving corrupted bytes.  ``acc`` survives the
    round trip so a restored session's decode recurrence is bit-exact
    without re-deriving the reduction arena from scratch."""

    __slots__ = ("session", "tenant", "priority", "seq_len",
                 "last_token", "acc", "hblocks", "crcs", "last_used")

    def __init__(self, session: str, tenant: str, priority: int,
                 seq_len: int, last_token: int, acc: int,
                 hblocks: np.ndarray, crcs: List[int], now: float):
        self.session = session
        self.tenant = tenant
        self.priority = priority
        self.seq_len = seq_len
        self.last_token = last_token
        self.acc = acc
        self.hblocks = hblocks           # np.int64 (n_blocks,)
        self.crcs = crcs                 # chained crc32 per position
        self.last_used = now


class PagedKvPool:
    """The paged KV arena.  Thread-safe; one per decode worker."""

    # cardinality cap for per-tenant eviction counters — the tenant
    # string is untrusted wire input (the admission controller's rule)
    MAX_TRACKED_TENANTS = 64

    _GUARDED_BY = {
        "_free": "_lock",
        "_tables": "_lock",
        "_refs": "_lock",
        "_prefix_index": "_lock",
        "_block_hash": "_lock",
        "_recent_evicted": "_lock",
        "_host_free": "_lock",
        "_spilled": "_lock",
        "_host_refs": "_lock",
        "_spill_map": "_lock",
        "_restoring": "_lock",
        "_spill_fault": "_lock",
        "_restore_us": "_lock",
        "_sweep_timer": "_lock",
        "_closed": "_lock",
        "_counters": "_counters_lock",
        "_tenant_labels": "_counters_lock",
    }

    # fablint custody contract (ISSUE 20).  A pin is owed an unpin; a
    # reservation is owed exactly one of commit / abort / return (the
    # restore path resolves through _finish_restore_locked); the block
    # refcounts free through _free_session_locked (or an inline
    # guarded decrement), the host-tier refcounts through
    # _host_unref_locked.  The methods named here are the protocol
    # implementation and are exempt from the acquire-release rule;
    # everything else that acquires must release on every exit path.
    _CUSTODY = {
        "pin": ("unpin",),
        "pinned": ("unpin",),
        "_reserve_locked": ("_commit_locked", "_abort_fill_locked",
                            "_return_blocks_locked",
                            "_finish_restore_locked"),
        "_refs": ("_free_session_locked", "_return_blocks_locked"),
        "_host_refs": ("_host_unref_locked", "_finish_restore_locked"),
    }

    def __init__(self, options: KvPoolOptions,
                 now: Optional[Callable[[], float]] = None):
        o = options
        self.options = o
        self._now = now or time.monotonic
        self._lock = _dbg.make_lock("PagedKvPool._lock")
        self._counters_lock = _dbg.make_lock("PagedKvPool._counters_lock")
        # the arenas are DELIBERATELY unguarded: a reserved block is off
        # the free list and in no table, so its rows have exactly one
        # writer (the in-flight fill) and no reader — the disjoint-row
        # discipline that makes the outside-the-lock fill safe
        self._store = np.zeros(
            (o.num_blocks, o.block_tokens * o.bytes_per_token), np.uint8)
        self._pos_sums = np.zeros((o.num_blocks, o.block_tokens), np.int64)
        # row-sum accumulator dtype: int32 sums measured 2.7x faster
        # than int64 on the uint8 arena (numpy SIMD), and a row of
        # bytes_per_token 255s fits int32 up to ~8.4 MB/token — fall
        # back to int64 beyond (the arena itself stays int64 either way)
        self._sum_dtype = (np.int32
                           if o.bytes_per_token * 255 < 2**31 - 1
                           else np.int64)
        # the batched decode step's gather surface: a VIEW over the
        # reduction arena (C-contiguous reshape shares memory), fixed
        # shape for the whole pool lifetime — jit-friendly
        self.pos_sums_flat = self._pos_sums.reshape(-1)
        self._free: List[int] = list(range(o.num_blocks - 1, -1, -1))
        self._tables: Dict[str, _KvSession] = {}
        # per-PHYSICAL-block refcount for every block owned by >= 1
        # session table (1 = private, >= 2 = prefix-shared); reserved
        # blocks mid-fill are in neither _free nor _refs, so
        # len(_free) + len(_refs) + in-flight == num_blocks always
        self._refs: Dict[int, int] = {}
        # chained-CRC prefix hash -> physical block, plus the reverse
        # map for unregistration at free time.  The index is a LOOKUP
        # ACCELERATOR only: every hit is byte-verified before sharing
        self._prefix_index: Dict[int, int] = {}
        self._block_hash: Dict[int, int] = {}
        # recently-evicted ids → reason, so a late Decode gets a typed
        # "re-prefill" shed instead of an unknown-session error
        self._recent_evicted: Dict[str, str] = {}
        # ---- host tier (ISSUE 19) — all empty when host_blocks == 0.
        # The host arena itself is unguarded for the same disjoint-row
        # reason as the device arenas: a host block is written exactly
        # once (at demote, under the lock) and read by at most one
        # restore, which holds its own host refcount for the copy.
        self._host_store = np.zeros(
            (o.host_blocks, o.block_tokens * o.bytes_per_token),
            np.uint8)
        self._host_free: List[int] = list(
            range(o.host_blocks - 1, -1, -1))
        self._spilled: Dict[str, _SpilledSession] = {}
        # per-HOST-block refcount: spilled sessions sharing a prefix
        # share ONE host copy (a shared block spills once); an in-flight
        # restore holds an extra count so a concurrent drop of the
        # record can never free host bytes mid-copy
        self._host_refs: Dict[int, int] = {}
        # live device block -> its host copy: the demote-time dedupe
        # accelerator.  An entry is valid exactly while the device
        # block's bytes are unchanged — invalidated on physical free,
        # on an in-place private write, and when the host copy frees
        self._spill_map: Dict[int, int] = {}
        self._restoring: set = set()
        self._spill_fault: Optional[str] = None   # test injection
        self._restore_us: deque = deque(maxlen=512)
        self._spill_health = None
        if o.host_blocks > 0:
            from ..ici.plane_health import register_plane
            self._spill_health = register_plane(
                "spill",
                retry_s=lambda: float(_flags.get_flag(
                    "serving_kv_spill_reprobe_s")))
        self._sweep_timer = None
        self._closed = False
        self.loads = bvar.Adder("serving_kv_pool_loads")
        self.bytes_in = bvar.Adder("serving_kv_pool_bytes_in")
        self.evictions = bvar.Adder("serving_kv_pool_evictions")
        self.expirations = bvar.Adder("serving_kv_pool_expired")
        # load_into fills that raised: the reservation aborted clean
        self.fill_aborts = bvar.Adder("serving_kv_pool_fill_aborts")
        # ISSUE 16 truth: blocks shared at commit, CoW splits, commit
        # re-checks that found a raced incumbent, and the fill-route
        # counters the concurrency tests assert per call
        self.prefix_hits = bvar.Adder("serving_kv_pool_prefix_hits")
        self.cow_splits = bvar.Adder("serving_kv_pool_cow_splits")
        self.commit_races = bvar.Adder("serving_kv_pool_commit_races")
        self.locked_fills = bvar.Adder("serving_kv_pool_locked_fills")
        self.unlocked_fills = bvar.Adder("serving_kv_pool_unlocked_fills")
        # ISSUE 19 tier truth: demote/restore round trips, restores
        # that failed byte verification (degraded to re-prefill), and
        # spilled sessions dropped under HOST-tier pressure
        self.demotions = bvar.Adder("serving_kv_pool_demotions")
        self.restores = bvar.Adder("serving_kv_pool_restores")
        self.restore_corrupt = bvar.Adder(
            "serving_kv_pool_restore_corrupt")
        self.host_evictions = bvar.Adder(
            "serving_kv_pool_host_evictions")
        self._counters: Dict[tuple, bvar.Adder] = {}
        self._tenant_labels: set = set()

    # ---- policy helpers -----------------------------------------------
    def _weight(self, tenant: str) -> int:
        from ..rpc.admission import tenant_weight_of
        return tenant_weight_of(self.options.tenant_weights,
                                self.options.default_tenant_weight,
                                tenant)

    def _clip_priority(self, priority: Optional[int]) -> int:
        pri = self.options.default_priority if priority is None \
            else priority
        return min(max(pri, 0), self.options.bands - 1)

    def _count(self, what: str, tenant: str) -> None:
        with self._counters_lock:
            if tenant and tenant not in self.options.tenant_weights \
                    and tenant not in self._tenant_labels:
                if len(self._tenant_labels) >= self.MAX_TRACKED_TENANTS:
                    tenant = "~other"
                else:
                    self._tenant_labels.add(tenant)
            key = (what, tenant)
            a = self._counters.get(key)
            if a is None:
                safe = bvar.to_underscored_name(tenant or "shared")
                a = self._counters[key] = bvar.Adder(
                    f"serving_kv_{what}_{safe}")
        a << 1

    # ---- load / release -----------------------------------------------
    def blocks_for(self, seq_len: int) -> int:
        bt = self.options.block_tokens
        return (seq_len + bt - 1) // bt

    def load(self, session: str, token_rows: np.ndarray, *,
             last_token: int, tenant: str = "",
             priority: Optional[int] = None) -> _KvSession:
        """Page a session's KV in.  ``token_rows`` is token-major uint8,
        shape ``(seq_len, bytes_per_token)`` — the caller transposes the
        model's layer-major layout once here, so every block row is one
        token's bytes and paging never splits a token.  Raises
        :class:`PoolSaturated` when eviction cannot make room.

        Since ISSUE 16 this is a delegation to :meth:`load_into` with a
        row-copy fill: both entry surfaces ride the SAME
        reserve/fill/commit shape (and the same flags), so locking
        discipline, abort semantics, prefix sharing, and the concurrent
        fill can never drift between them."""
        o = self.options
        rows = np.ascontiguousarray(token_rows, dtype=np.uint8)
        if rows.ndim != 2 or rows.shape[1] != o.bytes_per_token:
            raise ValueError(
                f"token_rows must be (seq_len, {o.bytes_per_token}), "
                f"got {rows.shape}")
        seq_len = rows.shape[0]
        if seq_len <= 0:
            # a 0-token session would build an empty block table the
            # batched step cannot index — reject at the boundary
            raise ValueError("token_rows must hold at least one token")

        def fill(views: List[np.ndarray]) -> None:
            off = 0
            for v in views:
                n = v.shape[0]
                v[:] = rows[off:off + n]
                off += n

        return self.load_into(session, seq_len, fill,
                              last_token=last_token, tenant=tenant,
                              priority=priority)

    def load_into(self, session: str, seq_len: int,
                  fill: Callable[[List[np.ndarray]], None], *,
                  last_token: int, tenant: str = "",
                  priority: Optional[int] = None) -> _KvSession:
        """Reserve the block table FIRST, then fill blocks IN PLACE —
        the zero-intermediate-copy loader surface (ISSUE 15).

        ``fill(views)`` receives an ordered list of writable
        ``(n_rows, bytes_per_token)`` uint8 views — one per CONTIGUOUS
        EXTENT of reserved blocks, together covering exactly
        ``seq_len`` token rows (a fresh or steady pool allocates one
        extent, so the common fill is ONE strided pass; a fragmented
        pool hands out more, smaller views).  It must write every row
        (a partial write would publish a table over stale arena bytes).

        With ``serving_kv_concurrent_fill`` ON (the default) the fill
        runs OUTSIDE the pool lock — the ISSUE-16 concurrency lever:
        reserved blocks are off the free list and in no table, so no
        eviction, expiry, or concurrent loader can touch their arena
        rows, and two LoadKv fills scatter in parallel.  The commit
        then RE-CHECKS under the lock: a pool closed mid-fill raises
        (``close()`` already reclaimed every block); a concurrent
        loader that committed the same session id mid-fill is replaced
        last-commit-wins when unpinned, or aborts THIS fill with
        :class:`SessionBusy` when the incumbent got pinned (counted in
        ``commit_races`` either way).  OFF restores the PR-15
        hold-through-the-fill discipline byte-for-byte — in that shape
        ``fill`` must not call back into this pool.

        If ``fill`` raises, the reservation ABORTS clean: blocks
        return to the free list, no session entry is created — a
        same-session RELOAD keeps its previous KV valid whenever the
        free list alone covered the reservation (see
        ``_reserve_locked``) — and the exception propagates (the RPC
        layer's eviction-mid-load / bad-source path).  After a
        successful fill the pool derives the reduction arena
        (``pos_sums``/``acc``) from the written bytes, zeroes the
        partial tail so no prior tenant's bytes survive adoption,
        dedupes full blocks against the prefix index
        (``serving_kv_prefix_share``), and commits the table —
        byte-for-byte the state ``load`` builds from a pre-materialized
        array."""
        o = self.options
        if seq_len <= 0:
            raise ValueError("seq_len must be >= 1")
        pri = self._clip_priority(priority)
        need = self.blocks_for(seq_len)
        now = self._now()
        bpt = o.bytes_per_token
        if _flags.get_flag("serving_kv_concurrent_fill"):
            with self._lock:
                blocks, deferred_old = self._reserve_locked(session, need,
                                                            pri)
            _ledger.acquire("kv.reserve", (id(self), id(blocks)))
            # the fill below touches only the unguarded arenas through
            # rows nothing else references (reserved blocks are
            # invisible to every other pool operation).  EVERYTHING
            # between the reserve and the commit sits inside the try:
            # the extent-view build and the session construction can
            # raise under allocator pressure just like the fill, and
            # an abort must reach the reservation from every one of
            # those edges (ISSUE 20 — the custody pass proves this)
            try:
                extents, views = self._extent_views(blocks, seq_len)
                fill(views)
                acc = self._derive_sums(extents, views, seq_len)
                s = _KvSession(session, tenant, pri, seq_len, last_token,
                               acc, blocks, now)
            except BaseException:
                # abort clean: the reservation never became a session
                with self._lock:
                    self._abort_fill_locked(blocks)
                _ledger.release("kv.reserve", (id(self), id(blocks)))
                self.fill_aborts << 1
                raise
            try:
                with self._lock:
                    self._commit_locked(s, deferred_old)
            finally:
                # a SessionBusy / closed-pool commit refusal already
                # returned the blocks internally: custody ends either way
                _ledger.release("kv.reserve", (id(self), id(blocks)))
            self.unlocked_fills << 1
        else:
            with self._lock:
                blocks, deferred_old = self._reserve_locked(session, need,
                                                            pri)
                _ledger.acquire("kv.reserve", (id(self), id(blocks)))
                try:
                    extents, views = self._extent_views(blocks, seq_len)
                    fill(views)
                    acc = self._derive_sums(extents, views, seq_len)
                    s = _KvSession(session, tenant, pri, seq_len,
                                   last_token, acc, blocks, now)
                except BaseException:
                    # abort clean: the reservation never became a
                    # session (close() cannot race — we hold the lock)
                    self._return_blocks_locked(blocks)
                    _ledger.release("kv.reserve", (id(self), id(blocks)))
                    self.fill_aborts << 1
                    raise
                try:
                    self._commit_locked(s, deferred_old)
                finally:
                    _ledger.release("kv.reserve",
                                    (id(self), id(blocks)))
            self.locked_fills << 1
        self.loads << 1
        self.bytes_in << seq_len * bpt
        return s

    def _extent_views(self, blocks: np.ndarray, seq_len: int):
        """Coalesce a reservation into contiguous extents and build the
        writable fill views: per-extent numpy ops amortize over whole
        runs of blocks instead of paying call overhead per 16-token
        block.  Touches only the unguarded arena (reserved rows have
        exactly one writer), so it runs with or without the pool lock."""
        o = self.options
        bt, bpt = o.block_tokens, o.bytes_per_token
        need = len(blocks)
        extents = []              # (first_block, n_blocks, n_rows)
        left = seq_len
        b0 = int(blocks[0])
        k = 1
        for i in range(1, need):
            b = int(blocks[i])
            if b == b0 + k:
                k += 1
                continue
            rows = min(left, k * bt)
            extents.append((b0, k, rows))
            left -= rows
            b0, k = b, 1
        extents.append((b0, k, min(left, k * bt)))
        views = [self._store[e0:e0 + ek].reshape(-1, bpt)[:rows]
                 for e0, ek, rows in extents]
        return extents, views

    def _derive_sums(self, extents, views, seq_len: int) -> int:
        """Derive the reduction arena from the filled bytes and zero the
        partial tail so no prior tenant's bytes survive adoption.
        Returns the session accumulator.  Unguarded-arena-only, same
        rationale as :meth:`_extent_views`."""
        o = self.options
        bt, bpt = o.block_tokens, o.bytes_per_token
        acc = 0
        for (e0, ek, rows), v in zip(extents, views):
            sums = v.sum(axis=1, dtype=self._sum_dtype)
            ps = self._pos_sums[e0:e0 + ek].reshape(-1)
            ps[:rows] = sums
            acc += int(sums.sum(dtype=np.int64))
            if rows < ek * bt:
                # zero the tail so no prior tenant's bytes survive
                # in the partially-filled final block
                ps[rows:] = 0
                self._store[e0:e0 + ek].reshape(-1)[rows * bpt:] = 0
        return acc

    # fablint: lock-held(_lock)
    def _reserve_locked(self, session: str, need: int, pri: int):
        """Allocate ``need`` blocks for ``session`` (evicting under
        pressure per the band/weight/LRU policy): the shared first half
        of ``load`` and ``load_into``.  Returns ``(blocks,
        deferred_old)`` — blocks are OFF the free list and in no table
        (invisible to eviction, expiry, and every concurrent loader);
        the caller fills them and commits (or returns them on a fill
        failure).  A same-session reload keeps the OLD entry alive as
        ``deferred_old`` whenever the free list alone covers the
        reservation, so an aborted fill leaves the previous KV valid
        (``_commit_locked`` frees it); only a reservation that NEEDS
        the old blocks for capacity reclaims them up front — the one
        case an abort genuinely cannot restore."""
        o = self.options
        if need > o.num_blocks:
            raise PoolSaturated(need, o.num_blocks)
        if self._closed:
            raise RuntimeError("kv pool is closed")
        old = self._tables.get(session)
        deferred_old = None
        if old is not None:
            if old.pinned:
                # NEVER free a rostered session's blocks out from
                # under the running batched step
                raise SessionBusy(session)
            if need <= len(self._free):
                deferred_old = old
            else:
                # a re-prefill bigger than the free space reclaims its
                # own previous table first
                self._free_session_locked(old, "reloaded")
        if need > len(self._free):
            spill = self._spill_usable_locked()
            victims = self._pick_victims_locked(
                need - len(self._free), pri, spill=spill)
            if victims is None:
                raise PoolSaturated(need, len(self._free))
            for v in victims:
                # eviction becomes DEMOTION when the host tier is
                # usable; a per-victim demote failure (host arena
                # full / injected IO fault) falls back to the PR-16
                # evict, so the picker's free-bytes simulation stays
                # exact either way — _free_session_locked runs under
                # both outcomes, only the reason differs
                if spill and self._demote_session_locked(v):
                    continue
                self._free_session_locked(v, "pressure")
        blocks = np.empty(need, np.int64)
        for k in range(need):
            blocks[k] = self._free.pop()
        return blocks, deferred_old

    # fablint: lock-held(_lock)
    def _abort_fill_locked(self, blocks) -> None:
        """Return an aborted outside-the-lock reservation — UNLESS the
        pool closed mid-fill, whose free-list rebuild already reclaimed
        every block (returning ours again would double-count them)."""
        if not self._closed:
            self._return_blocks_locked(blocks)

    # fablint: lock-held(_lock)
    def _commit_locked(self, s: _KvSession, deferred_old) -> None:
        """Publish a filled reservation: the COMMIT-TIME RE-CHECK of
        the outside-the-lock fill (a no-op re-check when the caller
        held the lock through the fill).  Order matters: the raced/
        pinned check FIRST (an abort must return the ORIGINAL blocks,
        never deduped substitutes another session owns), then prefix
        dedupe + refcounts, and only then the incumbent's free — so a
        same-content reload SHARES its predecessor's blocks for the
        one lock hold both are alive, and the decrement leaves them
        owned by the new entry alone."""
        if self._closed:
            # close() raced the fill: its free-list rebuild already
            # reclaimed every block — publishing (or returning) now
            # would resurrect custody close() ended
            raise RuntimeError("kv pool is closed")
        cur = self._tables.get(s.session)
        if cur is not None:
            if cur is not deferred_old:
                # a concurrent loader committed this session id mid-fill
                self.commit_races << 1
            if cur.pinned:
                # the incumbent — a raced commit OR our own
                # deferred_old that a roster/view pinned during the
                # outside-the-lock fill window — is being READ right
                # now: OUR fill aborts, its blocks stay intact (the
                # reserve-time pinned check cannot see a pin that
                # arrives mid-fill, so the re-check must)
                self._return_blocks_locked(s.blocks)
                raise SessionBusy(s.session)
            # last-commit-wins: retire the raced incumbent (after
            # dedupe below would be too late — but sharing against it
            # is still possible because the free only happens further
            # down, after refcounts pin the shared blocks)
        if _flags.get_flag("serving_kv_prefix_share"):
            self._dedupe_blocks_locked(s)
        for b in s.blocks:
            b = int(b)
            self._refs[b] = self._refs.get(b, 0) + 1
        # fresh bytes supersede any parked host copy of this id — a
        # re-prefill must never leave a stale spilled record behind
        # for a later restore to resurrect
        self._drop_spilled_locked(s.session)
        if cur is not None:
            # deferred_old or the raced unpinned incumbent: either way
            # the fill succeeded, NOW retire the replaced table (still
            # under the same lock hold, so no reader ever saw a gap)
            self._free_session_locked(cur, "reloaded")
        self._tables[s.session] = s
        self._recent_evicted.pop(s.session, None)
        self._schedule_sweep_locked()

    # fablint: lock-held(_lock)
    def _dedupe_blocks_locked(self, s: _KvSession) -> None:
        """Map ``s``'s FULL blocks onto existing physical blocks where
        a byte-identical block-aligned prefix already lives in the pool
        (ISSUE 16).  The key is a CHAINED crc32 over the block run, so
        equal keys mean equal position-in-prefix candidates; every hit
        is BYTE-VERIFIED before substitution, so a collision degrades
        to a miss, never to sharing wrong bytes.  Sharing stops at the
        first miss (prefixes only — a mid-sequence match cannot share
        because the chain key diverged), but hashing continues so this
        session's full blocks register as donors for longer prefixes.
        Partial tail blocks never share and never register."""
        o = self.options
        blocks = s.blocks
        full = s.seq_len // o.block_tokens
        h = 0
        sharing = True
        new_blocks = None
        returned = []
        for k in range(full):
            blk = int(blocks[k])
            data = self._store[blk]
            h = zlib.crc32(data, h)
            if sharing:
                eb = self._prefix_index.get(h)
                if (eb is not None and eb != blk and eb in self._refs
                        and np.array_equal(self._store[eb], data)):  # fablint: ignore[blocking-under-lock] dedupe byte-verify: one block-sized compare under _lock is the accepted PR-16 collision fence; moving it outside would race the donor's free (ROADMAP 5 residue)
                    # verified content match: map this position onto
                    # the existing physical block, hand ours back
                    if new_blocks is None:
                        new_blocks = blocks.copy()
                    new_blocks[k] = eb
                    returned.append(blk)
                    self.prefix_hits << 1
                    continue
                sharing = False
            if h not in self._prefix_index:
                self._prefix_index[h] = blk
                self._block_hash[blk] = h
        if new_blocks is not None:
            s.blocks = new_blocks
            s.contiguous = bool((np.diff(new_blocks) == 1).all())
            self._return_blocks_locked(returned)

    # fablint: lock-held(_lock)
    def _unregister_block_locked(self, blk: int) -> None:
        """Drop a freed (or about-to-be-overwritten) block from the
        prefix index so no future load shares stale content."""
        h = self._block_hash.pop(blk, None)
        if h is not None and self._prefix_index.get(h) == blk:
            del self._prefix_index[h]

    # fablint: lock-held(_lock)
    def _pick_victims_locked(self, blocks_needed: int,
                             requester_pri: int, exclude=None,
                             spill: bool = False):
        """Eviction order under pressure: most-sheddable band first,
        lighter tenants before heavier inside a band, LRU inside a
        class; never a band more protected than the requester's.  A
        victim only contributes the blocks that would ACTUALLY free —
        the refcount decrements are simulated cumulatively across the
        victim list, so two sessions sharing a prefix free its blocks
        only when BOTH are on the list.  ``exclude`` fences one session
        out of the candidate set (``write_rows`` evicting on behalf of
        the session it is mutating must never pick that session).

        ``spill=True`` (ISSUE 19): victims will be DEMOTED, not killed,
        so the ordering PREFERS taking a whole shared-owner set over an
        unshared live session of the same protection class — the set's
        blocks spill ONCE for all its owners, and taking it whole is
        the only way its shared blocks free at all (PR 16's picker
        saturated there).  Candidates are grouped into shared-block
        connected components; a group sorts by its MOST PROTECTED
        member's band (taking any member means taking the set, so the
        set is as protected as its most protected owner), shared sets
        before singletons within a band, then lightest member weight,
        then oldest member LRU.  The cumulative free-bytes simulation
        is IDENTICAL to the ungrouped path — grouping only reorders."""
        cands = [s for s in self._tables.values()
                 if not s.pinned and s.priority >= requester_pri
                 and s is not exclude]
        if spill and len(cands) > 1:
            parent = list(range(len(cands)))

            def find(x: int) -> int:
                while parent[x] != x:
                    parent[x] = parent[parent[x]]
                    x = parent[x]
                return x

            block_owner: Dict[int, int] = {}
            for i, s in enumerate(cands):
                for b in s.blocks:
                    b = int(b)
                    if self._refs.get(b, 1) > 1:
                        j = block_owner.get(b)
                        if j is None:
                            block_owner[b] = i
                        else:
                            ra, rb = find(i), find(j)
                            if ra != rb:
                                parent[rb] = ra
            comps: Dict[int, List[_KvSession]] = {}
            for i, s in enumerate(cands):
                comps.setdefault(find(i), []).append(s)
            groups = list(comps.values())
            groups.sort(key=lambda g: (
                -min(s.priority for s in g),
                0 if len(g) > 1 else 1,
                min(self._weight(s.tenant) for s in g),
                min(s.last_used for s in g)))
            for g in groups:
                g.sort(key=lambda s: (-s.priority,
                                      self._weight(s.tenant),
                                      s.last_used))
            cands = [s for g in groups for s in g]
        else:
            cands.sort(key=lambda s: (-s.priority,
                                      self._weight(s.tenant),
                                      s.last_used))
        victims, have = [], 0
        sim: Dict[int, int] = {}
        for s in cands:
            if have >= blocks_needed:
                break
            victims.append(s)
            for b in s.blocks:
                b = int(b)
                taken = sim.get(b, 0)
                sim[b] = taken + 1
                if self._refs.get(b, 1) - taken == 1:
                    have += 1
        return victims if have >= blocks_needed else None

    # fablint: lock-held(_lock)
    def _return_blocks_locked(self, blocks) -> None:
        """Put blocks back KEEPING the free list sorted descending —
        the invariant that makes ``pop()`` hand out ASCENDING runs, so
        ``load_into`` reservations coalesce into few contiguous extents
        (one strided fill pass each) instead of 1-block shards.  Timsort
        on the mostly-sorted list is microseconds at pool sizes."""
        self._free.extend(int(b) for b in blocks)
        self._free.sort(reverse=True)

    # fablint: lock-held(_lock)
    def _free_session_locked(self, s: _KvSession, reason: str) -> None:
        """Retire a session's table: DECREMENT each block's refcount,
        physically freeing (and unregistering from the prefix index)
        only the blocks that hit zero — a prefix another session still
        shares survives its co-owner's eviction/release/expiry."""
        self._tables.pop(s.session, None)
        dead = []
        for b in s.blocks:
            b = int(b)
            r = self._refs.get(b, 1) - 1
            if r <= 0:
                self._refs.pop(b, None)
                self._unregister_block_locked(b)
                # a physically-freed block's bytes are about to be
                # rewritten by the next reservation: its host-copy
                # mapping is stale the moment it leaves custody
                self._spill_map.pop(b, None)
                dead.append(b)
            else:
                self._refs[b] = r
        if dead:
            self._return_blocks_locked(dead)
        if reason in ("pressure", "expired"):
            self._recent_evicted[s.session] = reason
            while len(self._recent_evicted) > 256:
                self._recent_evicted.pop(
                    next(iter(self._recent_evicted)))
        if reason == "expired":
            self.expirations << 1
        elif reason == "pressure":
            self.evictions << 1
        elif reason == "spilled":
            # demotion, not death: the session is retrievable from the
            # host tier, so it gets neither a _recent_evicted entry nor
            # an eviction count
            self.demotions << 1
        if reason == "released":
            self._count("released", s.tenant)
        elif reason == "spilled":
            self._count("spilled", s.tenant)
        else:
            self._count(f"evicted_{reason}", s.tenant)

    # ---- host tier: spill / restore (ISSUE 19) -------------------------
    # fablint: lock-held(_lock)
    def _spill_usable_locked(self) -> bool:
        """Demotion is on exactly when the pool HAS a host arena, the
        A/B flag says so, and the spill plane-health row is usable —
        a latched IO failure turns pressure back into PR-16 eviction
        until the timer latch lapses and the plane revives."""
        return (self.options.host_blocks > 0
                and bool(_flags.get_flag("serving_kv_spill"))
                and self._spill_health.usable())

    # fablint: lock-held(_lock)
    def _demote_session_locked(self, s: _KvSession) -> bool:
        """Copy ``s``'s blocks into the host arena and retire its
        device table ("spilled" — retrievable, not dead).  A device
        block that already has a live host copy (a co-owner spilled
        first, or shares the block with an already-spilled session)
        reuses it with a refcount bump — a SHARED BLOCK SPILLS ONCE.
        Returns False without side effects on the session when the
        host tier cannot take it (arena full even after reclaiming
        older spilled sessions, or the injected IO fault) — the caller
        falls back to eviction."""
        if self._spill_fault == "demote":
            # injected demote-IO failure: latch the plane down so
            # pressure stops routing victims at a failing host arena
            self._spill_health.mark_down("demote_io")
            return False
        need_new = 0
        for b in s.blocks:
            b = int(b)
            if b not in self._spill_map:
                need_new += 1
        if need_new > len(self._host_free) and \
                not self._host_reclaim_locked(
                    need_new - len(self._host_free), s.priority):
            return False
        hblocks = np.empty(len(s.blocks), np.int64)
        crcs: List[int] = []
        chain = 0
        new_host: List[int] = []
        for k, b in enumerate(s.blocks):
            b = int(b)
            data = self._store[b]
            chain = zlib.crc32(data, chain)
            crcs.append(chain)
            hb = self._spill_map.get(b)
            if hb is None:
                hb = self._host_free.pop()
                self._host_store[hb] = data
                self._spill_map[b] = hb
                new_host.append(hb)
            # fablint: custody-moved(spill-record) the ref lives in the _SpilledSession entry below; _drop_spilled_locked / _host_unref_locked balance it
            self._host_refs[hb] = self._host_refs.get(hb, 0) + 1
            hblocks[k] = hb
        now = self._now()
        self._spilled[s.session] = _SpilledSession(
            s.session, s.tenant, s.priority, s.seq_len, s.last_token,
            s.acc, hblocks, crcs, now)
        self._free_session_locked(s, "spilled")
        return True

    # fablint: lock-held(_lock)
    def _host_reclaim_locked(self, shortage: int,
                             requester_pri: int) -> bool:
        """Make room in the HOST arena by dropping the most sheddable
        spilled sessions — same band/weight/LRU order and the same
        cumulative refcount simulation as the device picker, fenced to
        bands no more protected than the demoting session's.  Sessions
        mid-restore are skipped (their host bytes are being read).
        Dropped sessions die for real: typed "pressure" shed."""
        cands = [sp for sess, sp in self._spilled.items()
                 if sess not in self._restoring
                 and sp.priority >= requester_pri]
        cands.sort(key=lambda sp: (-sp.priority,
                                   self._weight(sp.tenant),
                                   sp.last_used))
        victims, have = [], 0
        sim: Dict[int, int] = {}
        for sp in cands:
            if have >= shortage:
                break
            victims.append(sp)
            for h in sp.hblocks:
                h = int(h)
                taken = sim.get(h, 0)
                sim[h] = taken + 1
                if self._host_refs.get(h, 1) - taken == 1:
                    have += 1
        if have < shortage:
            return False
        for sp in victims:
            self._drop_spilled_locked(sp.session)
            self._recent_evicted[sp.session] = "pressure"
            while len(self._recent_evicted) > 256:
                self._recent_evicted.pop(
                    next(iter(self._recent_evicted)))
            self.host_evictions << 1
            self._count("evicted_pressure", sp.tenant)
        return True

    # fablint: lock-held(_lock)
    def _drop_spilled_locked(self, session: str) -> None:
        """Retire one spilled record: decrement its host refcounts,
        freeing (and unmapping) only the host blocks that hit zero."""
        sp = self._spilled.pop(session, None)
        if sp is not None:
            self._host_unref_locked(sp.hblocks)

    # fablint: lock-held(_lock)
    def _host_unref_locked(self, hblocks) -> None:
        dead = []
        for h in hblocks:
            h = int(h)
            r = self._host_refs.get(h, 1) - 1
            if r <= 0:
                self._host_refs.pop(h, None)
                dead.append(h)
            else:
                self._host_refs[h] = r
        if dead:
            dead_set = set(dead)
            # a freed host block's device->host mapping is stale: a
            # later demote must never alias a recycled host slot
            for b in [b for b, h in self._spill_map.items()
                      if h in dead_set]:
                del self._spill_map[b]
            self._host_free.extend(dead)
            self._host_free.sort(reverse=True)

    def _maybe_restore(self, session: str) -> None:
        """Fault a spilled session back in if (and only if) it is
        host-resident — the cheap pre-check every lookup surface
        calls before taking its own locked path."""
        with self._lock:
            if session in self._tables or session not in self._spilled:
                return
        self._restore(session)

    def _restore(self, session: str) -> Optional[_KvSession]:
        """Bring a spilled session back to the device tier, riding the
        SAME reserve / fill-outside-the-lock / commit shape as
        ``load_into``: device blocks reserved under the lock (evicting
        or demoting others under the session's own priority), the
        host→device copy and reduction-arena rebuild run OUTSIDE it
        (the restore holds its own host refcounts so a concurrent drop
        of the record cannot free the bytes mid-copy), and the commit
        re-checks under a relock.  The chained CRC recorded at demote
        is recomputed from the HOST bytes during the copy: any
        mismatch aborts the restore and the session degrades to a
        typed "corrupt" re-prefill shed — wrong bytes are never
        published.  Returns None when the restore could not happen
        (device saturation, lost race, IO fault) — the caller sheds."""
        o = self.options
        bt, bpt = o.block_tokens, o.bytes_per_token
        t0 = time.perf_counter_ns()
        while True:
            with self._lock:
                s = self._tables.get(session)
                if s is not None:
                    return s
                sp = self._spilled.get(session)
                if sp is None:
                    return None
                if session not in self._restoring:
                    self._restoring.add(session)
                    try:
                        blocks, _ = self._reserve_locked(
                            session, len(sp.hblocks), sp.priority)
                    except PoolSaturated:
                        # no device room even after pressure: the
                        # session STAYS spilled (retryable shed), the
                        # host copy intact
                        self._restoring.discard(session)
                        return None
                    for h in sp.hblocks:
                        self._host_refs[int(h)] += 1
                    _ledger.acquire("kv.reserve",
                                    (id(self), id(blocks)))
                    fault = self._spill_fault
                    break
            # another thread is restoring this session: wait it out
            time.sleep(0.0005)
        # ---- outside the lock: reserved rows have exactly one writer,
        # and our extra host refs pin the source bytes.  The copy sits
        # inside a try: an allocator failure mid-copy must still drop
        # the host refs and return the reservation (ISSUE 20), and
        # EVERY outcome resolves through the one declared custody exit,
        # _finish_restore_locked
        ok = True
        try:
            io_fail = fault == "restore"
            if not io_fail:
                chain = 0
                for k in range(len(blocks)):
                    data = self._host_store[int(sp.hblocks[k])]
                    chain = zlib.crc32(data, chain)
                    if chain != sp.crcs[k]:
                        ok = False
                        break
                    b = int(blocks[k])
                    self._store[b] = data
                    self._pos_sums[b] = self._store[b].reshape(
                        bt, bpt).sum(axis=1, dtype=np.int64)
            now = self._now()
        except BaseException:
            with self._lock:
                self._finish_restore_locked(session, sp, blocks, t0,
                                            ok=False, io_fail=False,
                                            now=None, failed=True)
            raise
        with self._lock:
            return self._finish_restore_locked(session, sp, blocks, t0,
                                               ok=ok, io_fail=io_fail,
                                               now=now)

    # fablint: lock-held(_lock)
    def _finish_restore_locked(self, session: str, sp, blocks, t0, *,
                               ok: bool, io_fail: bool,
                               now: Optional[float],
                               failed: bool = False):
        """The restore's single custody-resolution point, declared as
        the release of BOTH the device reservation and the restore's
        host refs: exactly one of commit / return-blocks / close-race
        custody-end happens here, under one lock hold."""
        _ledger.release("kv.reserve", (id(self), id(blocks)))
        self._restoring.discard(session)
        if self._closed:
            # close() rebuilt the free list and cleared the host
            # tier — nothing left to return or unref
            return None
        self._host_unref_locked(sp.hblocks)
        if failed:
            # the outside-the-lock copy RAISED (allocator pressure /
            # test hook): host record intact, reservation returns, the
            # exception propagates to the caller
            self._return_blocks_locked(blocks)
            return None
        if io_fail:
            # transport failed, host bytes presumed intact: keep
            # the record, latch the plane, shed
            self._return_blocks_locked(blocks)
            self._spill_health.mark_down("restore_io")
            return None
        if not ok:
            # byte verification failed: the host copy is corrupt —
            # drop it and degrade to a typed re-prefill, NOT a
            # plane event (corruption is not plane death)
            self._return_blocks_locked(blocks)
            if self._spilled.get(session) is sp:
                self._drop_spilled_locked(session)
            self._recent_evicted[session] = "corrupt"
            while len(self._recent_evicted) > 256:
                self._recent_evicted.pop(
                    next(iter(self._recent_evicted)))
            self.restore_corrupt << 1
            return None
        cur = self._tables.get(session)
        if cur is not None:
            # a re-prefill committed fresh bytes mid-restore: the
            # fresh load wins, our copy aborts
            self._return_blocks_locked(blocks)
            return cur
        if self._spilled.get(session) is not sp:
            # the record was released/expired/reclaimed mid-copy
            self._return_blocks_locked(blocks)
            return None
        s = _KvSession(session, sp.tenant, sp.priority, sp.seq_len,
                       sp.last_token, sp.acc, blocks, now)
        # same commit as a load: prefix dedupe means the FIRST
        # restored co-owner re-registers the shared blocks and
        # every later restore maps onto them — one physical copy
        # restores N sessions
        self._commit_locked(s, None)
        self._drop_spilled_locked(session)
        self.restores << 1
        self._restore_us.append(
            (time.perf_counter_ns() - t0) // 1000)
        return s

    def spill(self, session: str) -> bool:
        """Demote one session to the host tier NOW — the autoscaler's
        drain surface (scale-down demotes its live sessions instead of
        killing them).  A pinned session refuses with
        :class:`SessionBusy` (it is being read); False when the
        session is unknown or the host tier cannot take it."""
        with self._lock:
            s = self._tables.get(session)
            if s is None:
                return False
            if s.pinned:
                raise SessionBusy(session)
            if not self._spill_usable_locked():
                return False
            return self._demote_session_locked(s)

    def spilled_sessions(self) -> List[str]:
        with self._lock:
            return list(self._spilled)

    def inject_spill_fault(self, mode: Optional[str]) -> None:
        """Chaos hook: ``"demote"`` fails every demote attempt,
        ``"restore"`` fails every restore copy (both latch the spill
        plane down), ``None`` heals."""
        if mode not in (None, "demote", "restore"):
            raise ValueError(f"unknown spill fault {mode!r}")
        with self._lock:
            self._spill_fault = mode

    def release(self, session: str) -> bool:
        """Session finished: return its blocks (the decode-complete
        path).  Idempotent.  A PINNED session is not freed NOW — a pin
        means a roster entry or a zero-copy snapshot view is still
        reading these blocks, and freeing them would hand the bytes to
        the next loader mid-read — but the release is ACCEPTED and
        deferred to the last unpin (a race between a completion's
        release and a concurrent reader's pin window must not leak the
        blocks forever).  Every in-tree completion path unpins before
        releasing, so the deferral only fires on genuine races."""
        with self._lock:
            s = self._tables.get(session)
            if s is None:
                sp = self._spilled.get(session)
                if sp is not None:
                    # released while parked in the host tier: drop the
                    # record directly, no restore round trip.  An
                    # in-flight restore survives the drop (it holds
                    # its own host refs for the copy) and its commit
                    # re-check observes the record identity changed,
                    # aborting into "released" instead of publishing
                    self._drop_spilled_locked(session)
                    self._count("released", sp.tenant)
                    return True
                return False
            if s.pinned:
                s.release_pending = True
                return True
            self._free_session_locked(s, "released")
            return True

    # ---- mutation / CoW -------------------------------------------------
    def write_rows(self, session: str, start_token: int,
                   rows: np.ndarray) -> int:
        """Overwrite token rows of a LIVE session in place — the CoW
        mutation surface (ISSUE 16).  A target block whose refcount is
        > 1 is SPLIT first: a private copy is allocated (evicting under
        the session's own priority if the free list is empty), the
        shared original keeps its other owners untouched, and the
        session publishes a NEW blocks array (roster snapshots holding
        the old array keep reading the old — still valid — physical
        blocks).  A private block that is REGISTERED as a prefix donor
        is unregistered before the overwrite so no later load shares
        its stale hash.  Returns the number of CoW splits performed.
        Callers must not write under their own outstanding
        ``snapshot(view=True)`` read — the same discipline the roster
        pin documents."""
        o = self.options
        bt, bpt = o.block_tokens, o.bytes_per_token
        rows = np.ascontiguousarray(rows, dtype=np.uint8)
        if rows.ndim != 2 or rows.shape[1] != bpt:
            raise ValueError(
                f"rows must be (n, {bpt}), got {rows.shape}")
        n = rows.shape[0]
        if n <= 0:
            raise ValueError("rows must hold at least one token")
        now = self._now()
        self._maybe_restore(session)
        with self._lock:
            s = self._tables.get(session)
            if s is None or s.release_pending:
                raise KeyError(session)
            if start_token < 0 or start_token + n > s.seq_len:
                raise ValueError(
                    f"write [{start_token}, {start_token + n}) outside "
                    f"session of {s.seq_len} tokens")
            first_b = start_token // bt
            last_b = (start_token + n - 1) // bt
            new_blocks = None
            splits = 0
            for k in range(first_b, last_b + 1):
                blk = int(s.blocks[k] if new_blocks is None
                          else new_blocks[k])
                if self._refs.get(blk, 1) > 1 and not self._free:
                    # a split needs a free block: evict — NEVER the
                    # session being written (unpinned + a stale
                    # last_used would otherwise make it the likely
                    # LRU pick, and freeing it mid-write mutates a
                    # zombie over blocks back on the free list)
                    victims = self._pick_victims_locked(
                        1, s.priority, exclude=s)
                    if victims is None:
                        raise PoolSaturated(1, 0)
                    for v in victims:
                        self._free_session_locked(v, "pressure")
                if self._refs.get(blk, 1) > 1:
                    # CoW split: other sessions own these bytes too.
                    # RE-CHECKED after any eviction — taking the last
                    # co-owner drops the refcount to 1 and the block
                    # is already private; splitting then would strand
                    # it at refcount 0, off both the free list and
                    # every table
                    nb = self._free.pop()
                    self._store[nb] = self._store[blk]
                    self._pos_sums[nb] = self._pos_sums[blk]
                    self._refs[blk] -= 1
                    self._refs[nb] = 1
                    if new_blocks is None:
                        new_blocks = s.blocks.copy()
                    new_blocks[k] = nb
                    splits += 1
                    self.cow_splits << 1
                else:
                    # private — but a registered donor's content is
                    # about to change: drop it from the index, and
                    # drop any host copy mapped to the OLD bytes so a
                    # later demote re-copies instead of aliasing stale
                    # content
                    self._unregister_block_locked(blk)
                    self._spill_map.pop(blk, None)
            if new_blocks is not None:
                s.blocks = new_blocks
                s.contiguous = bool((np.diff(new_blocks) == 1).all())
            acc_delta = 0
            for k in range(first_b, last_b + 1):
                blk = int(s.blocks[k])
                t0 = max(start_token, k * bt)
                t1 = min(start_token + n, (k + 1) * bt)
                src = rows[t0 - start_token:t1 - start_token]
                sl0 = t0 - k * bt
                self._store[blk].reshape(bt, bpt)[
                    sl0:sl0 + (t1 - t0)] = src
                new_sums = src.sum(axis=1, dtype=self._sum_dtype)
                old = self._pos_sums[blk, sl0:sl0 + (t1 - t0)]
                acc_delta += (int(new_sums.sum(dtype=np.int64))
                              - int(old.sum(dtype=np.int64)))
                self._pos_sums[blk, sl0:sl0 + (t1 - t0)] = new_sums
            s.acc += acc_delta
            s.last_used = now
            return splits

    # ---- lookup / scheduler surface -----------------------------------
    def get(self, session: str) -> Optional[_KvSession]:
        with self._lock:
            s = self._tables.get(session)
            if s is not None or session not in self._spilled:
                return s
        # host-resident: fault it back in (the scheduler's roster add
        # and every read surface restore transparently)
        return self._restore(session)

    def evicted_reason(self, session: str) -> Optional[str]:
        """Why a recently-missing session is gone ("pressure" /
        "expired" / "corrupt"), so the RPC layer sheds with a typed
        re-prefill hint instead of an unknown-session error.  A
        session still PARKED in the host tier answers "spilled": its
        restore just failed transiently (device saturation / spill
        plane down) and a retry may succeed without a re-prefill."""
        with self._lock:
            if session in self._spilled:
                return "spilled"
            return self._recent_evicted.get(session)

    def touch(self, session: str) -> None:
        now = self._now()
        with self._lock:
            s = self._tables.get(session)
            if s is not None:
                s.last_used = now
            else:
                sp = self._spilled.get(session)
                if sp is not None:
                    # keep-alive reaches the host tier too — touch is
                    # deliberately NOT a restore trigger
                    sp.last_used = now

    def pin(self, session: str) -> bool:
        """Fence a session against eviction/expiry (step-roster entry
        or snapshot view; counted — pins nest).  False when the session
        is gone — including LOGICALLY gone: a deferred release
        (``release_pending``) means the pool already reported this
        session released, so no NEW reader may pin it while the last
        old reader drains.  A host-resident session is RESTORED first:
        a pin is a read-intent, and reads happen on the device tier."""
        self._maybe_restore(session)
        with self._lock:
            s = self._tables.get(session)
            if s is None or s.release_pending:
                return False
            s.pinned += 1
            _ledger.acquire("kv.pin", (id(self), session))
            return True

    def unpin(self, session: str) -> None:
        now = self._now()
        unbalanced = False
        with self._lock:
            s = self._tables.get(session)
            if s is not None:
                if s.pinned:
                    s.pinned -= 1
                    _ledger.release("kv.pin", (id(self), session),
                                    strict=True)
                else:
                    # an unpin nobody holds: swallowing it silently
                    # would let the NEXT unpin steal a live holder's
                    # fence (eviction under a reader's view) — scream
                    unbalanced = True
                s.last_used = now
                if not s.pinned and s.release_pending:
                    # a release arrived during the pin window: the last
                    # reader out frees the blocks
                    self._free_session_locked(s, "released")
        if unbalanced:
            from ..butil import logging as log
            log.error("kv pool: unbalanced unpin of session %r "
                      "(no pin held) — caller bug", session)

    def materialize(self, session: str) -> Optional[np.ndarray]:
        """COPY a session's token rows back out, ``(seq_len,
        bytes_per_token)`` — the byte-exactness tests' surface.  The
        read-only SYNC path should use ``snapshot(view=True)`` instead
        (the ISSUE-15 bugfix: a contiguous-extent session reads as a
        zero-copy pinned view, no reshape copy) — that surface returns
        an explicit ``is_view`` flag so the caller knows whether an
        unpin is owed; this one stays copy-only exactly so no caller
        can lose that flag."""
        snap = self.snapshot(session)
        return snap[0] if snap is not None else None

    def snapshot(self, session: str, *, view: bool = False):
        """``(rows, seq_len, last_token)`` under ONE lock acquisition —
        the sync decode path's atomic read (a separate get() +
        materialize() pair could straddle an eviction and pair the old
        entry's metadata with the new entry's bytes).

        ``view=True`` returns ``(rows, seq_len, last_token, is_view)``:
        when the session's blocks are one contiguous ascending extent,
        ``rows`` is a READ-ONLY view straight into the arena (no copy)
        and the session is PINNED — the caller MUST ``unpin(session)``
        when done reading, BEFORE any release.  The read-only flag is
        what keeps a view over PREFIX-SHARED blocks safe: no reader can
        scribble on bytes other sessions gather through.  Non-contiguous
        sessions (or pools under a straddle risk the caller can't
        fence) keep the copy, ``is_view=False``, no pin owed — the copy
        is what makes a concurrent eviction safe there, so it stays."""
        o = self.options
        self._maybe_restore(session)
        with self._lock:
            s = self._tables.get(session)
            if s is None or s.release_pending:
                # a deferred release means "already released" to every
                # NEW reader — only the pinned old readers drain it
                return None
            blocks = s.blocks
            if view and s.contiguous:
                b0 = int(blocks[0])
                rows = self._store[b0:b0 + len(blocks)].reshape(
                    -1, o.bytes_per_token)[:s.seq_len]
                rows.flags.writeable = False   # read-only: arena intact
                # fablint: custody-moved(caller) the view pin is owed back through the caller's unpin before any release — the documented view=True contract
                s.pinned += 1
                _ledger.acquire("kv.pin", (id(self), session))
                return rows, s.seq_len, s.last_token, True
            rows = self._store[blocks].reshape(
                -1, o.bytes_per_token)[:s.seq_len].copy()
            if view:
                return rows, s.seq_len, s.last_token, False
            return rows, s.seq_len, s.last_token

    # ---- expiry ---------------------------------------------------------
    # fablint: lock-held(_lock)
    def _schedule_sweep_locked(self) -> None:
        if (not self.options.use_timers or self._closed
                or self._sweep_timer is not None or not self._tables):
            return
        from ..bthread.timer_thread import TimerThread
        self._sweep_timer = TimerThread.instance().schedule_after(
            self._sweep, self.options.effective_sweep_s())

    def _sweep(self) -> None:
        """TimerThread callback: reclaim idle sessions past TTL — the
        traffic-independent expiry the ISSUE-14 bugfix demands."""
        with self._lock:
            self._sweep_timer = None
        self.expire_idle()
        with self._lock:
            self._schedule_sweep_locked()

    def expire_idle(self, now: Optional[float] = None) -> int:
        """Reclaim every unpinned session idle past ``ttl_s``.  Returns
        the count (also the manual surface for ``use_timers=False``
        tests)."""
        now = self._now() if now is None else now
        ttl = self.options.ttl_s
        n = 0
        with self._lock:
            for s in list(self._tables.values()):
                if not s.pinned and now - s.last_used > ttl:
                    self._free_session_locked(s, "expired")
                    n += 1
            for sess, sp in list(self._spilled.items()):
                # spilled sessions age out on the same TTL — an idle
                # host tier must not park bytes forever either
                if sess not in self._restoring \
                        and now - sp.last_used > ttl:
                    self._drop_spilled_locked(sess)
                    self._recent_evicted[sess] = "expired"
                    while len(self._recent_evicted) > 256:
                        self._recent_evicted.pop(
                            next(iter(self._recent_evicted)))
                    self.expirations << 1
                    self._count("evicted_expired", sp.tenant)
                    n += 1
        return n

    # ---- lifecycle / observability --------------------------------------
    def sessions(self) -> int:
        with self._lock:
            return len(self._tables)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            timer = self._sweep_timer
            self._sweep_timer = None
            self._tables.clear()
            self._refs.clear()
            self._prefix_index.clear()
            self._block_hash.clear()
            self._free = list(range(self.options.num_blocks - 1, -1, -1))
            self._spilled.clear()
            self._host_refs.clear()
            self._spill_map.clear()
            self._restoring.clear()
            self._host_free = list(
                range(self.options.host_blocks - 1, -1, -1))
        # custody ends with the pool: the free-list rebuild reclaimed
        # every block, outstanding pins die with the tables
        _ledger.drop_prefix("kv.pin", id(self))
        _ledger.drop_prefix("kv.reserve", id(self))
        if timer is not None:
            from ..bthread.timer_thread import TimerThread
            TimerThread.instance().unschedule(timer)

    def describe(self) -> dict:
        """The /status serving block's pool half."""
        o = self.options
        with self._lock:
            free = len(self._free)
            sessions = len(self._tables)
            pinned = sum(1 for s in self._tables.values() if s.pinned)
            per_tenant: Dict[str, int] = {}
            logical = 0
            for s in self._tables.values():
                key = s.tenant or "shared"
                per_tenant[key] = per_tenant.get(key, 0) + len(s.blocks)
                logical += len(s.blocks)
            shared = sum(1 for r in self._refs.values() if r > 1)
            physical = len(self._refs)
            host_free = len(self._host_free)
            spilled_sessions = len(self._spilled)
            spilled_blocks = len(self._host_refs)
            restore_us = sorted(self._restore_us)
            plane = (self._spill_health.snapshot()
                     if self._spill_health is not None else None)
        with self._counters_lock:
            by_class = {f"{what}[{tenant or 'shared'}]": a.get_value()
                        for (what, tenant), a in self._counters.items()}
        used = o.num_blocks - free
        return {
            "blocks_total": o.num_blocks,
            "blocks_free": free,
            "blocks_used": used,
            "block_tokens": o.block_tokens,
            "utilization": round(used / o.num_blocks, 3),
            "sessions": sessions,
            "pinned": pinned,
            "blocks_by_tenant": per_tenant,
            "loads": self.loads.get_value(),
            "bytes_in": self.bytes_in.get_value(),
            "evictions": self.evictions.get_value(),
            "expired": self.expirations.get_value(),
            "fill_aborts": self.fill_aborts.get_value(),
            "by_tenant": by_class,
            "ttl_s": o.ttl_s,
            # ISSUE 16: prefix-sharing / concurrent-fill truth —
            # logical blocks are session-table entries, physical are
            # distinct live blocks; the ratio is the capacity win
            "prefix": {
                "enabled": bool(_flags.get_flag(
                    "serving_kv_prefix_share")),
                "concurrent_fill": bool(_flags.get_flag(
                    "serving_kv_concurrent_fill")),
                "shared_blocks": shared,
                "prefix_hits": self.prefix_hits.get_value(),
                "cow_splits": self.cow_splits.get_value(),
                "commit_races": self.commit_races.get_value(),
                "locked_fills": self.locked_fills.get_value(),
                "unlocked_fills": self.unlocked_fills.get_value(),
                "logical_blocks": logical,
                "physical_blocks": physical,
                "sharing_ratio": (round(logical / physical, 3)
                                  if physical else 1.0),
            },
            # ISSUE 19: tiered-memory truth — resident vs host-parked
            # sessions, demote/restore round trips, restore latency,
            # and the spill plane-health row.  "migration" is the
            # PROCESS-WIDE pool-to-pool transfer ledger (the counters
            # live in serving/migration.py)
            "tiers": self._describe_tiers(
                sessions, host_free, spilled_sessions, spilled_blocks,
                restore_us, plane),
        }

    def _describe_tiers(self, resident: int, host_free: int,
                        spilled_sessions: int, spilled_blocks: int,
                        restore_us: List[int], plane) -> dict:
        o = self.options
        out = {
            "enabled": (o.host_blocks > 0
                        and bool(_flags.get_flag("serving_kv_spill"))),
            "host_blocks_total": o.host_blocks,
            "host_blocks_free": host_free,
            "resident_sessions": resident,
            "spilled_sessions": spilled_sessions,
            "spilled_blocks": spilled_blocks,
            "demotions": self.demotions.get_value(),
            "restores": self.restores.get_value(),
            "restore_corrupt": self.restore_corrupt.get_value(),
            "host_evictions": self.host_evictions.get_value(),
            "restore_p50_us": (restore_us[len(restore_us) // 2]
                               if restore_us else 0),
        }
        if plane is not None:
            out["plane"] = plane
        try:
            from . import migration as _migration
            out["migration"] = {**_migration.migration_stats(),
                                "scope": "process"}
        except Exception:   # pragma: no cover - import cycles only
            pass
        return out
