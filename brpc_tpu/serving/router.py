"""Load-aware prefill→decode routing over the LALB divided-weight
balancer (``policy/load_balancers.py``'s ``LocalityAwareLB``).

The serving front door needs something a plain LB channel cannot give
it: the router must KNOW which decode worker it chose (the prefill
worker pushes the KV handoff to that specific endpoint) and must feed
the decode call's outcome back into the balancer so a slow or dying
worker's divided weight collapses within one request time.  This helper
owns that loop:

  * membership — an explicit target list, or a naming url (``pod://``,
    ``mesh://``, ``list://``) re-resolved on a poll thread so elastic
    scale-up/down (the autoscaler's advertise/withdraw epoch moves)
    reaches the balancer within one refresh interval;
  * selection — ``pick()`` = LALB ``select_server`` (error-punished,
    in-flight-extrapolated divided weights) + the per-call exclusion
    list, so a retry after a dead worker never re-picks it;
  * feedback — ``feedback(url, error_code, latency_us)`` closes the
    loop the reference's LALB doctrine (docs/cn/lalb.md) is built on.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..butil import debug_sync as _dbg
from ..butil.endpoint import parse_endpoint
from ..policy.load_balancers import LocalityAwareLB


class LoadAwareRouter:
    """LALB selection + channel cache + elastic membership for a router
    service.  Thread-safe."""

    _GUARDED_BY = {
        "_channels": "_lock",
        "_picks": "_lock",
        "_affinity": "_lock",
        "_rebinds": "_lock",
        "_refresher": "_lock",
        "_closed": "_lock",
    }

    # session-affinity cardinality cap: session ids are wire input
    MAX_BOUND_SESSIONS = 8192

    def __init__(self, targets, channel_options=None,
                 refresh_interval_s: float = 0.5):
        from .. import rpc
        self._copts = channel_options or rpc.ChannelOptions(
            timeout_ms=60000)
        self._lock = _dbg.make_lock("LoadAwareRouter._lock")
        self._lb = LocalityAwareLB()
        self._channels: Dict[str, object] = {}
        self._picks: Dict[str, int] = {}
        # session -> decode worker url: the live-migration cutover
        # surface (ISSUE 19).  A rebind IS the atomic routing flip —
        # one dict write under the lock, so a reader sees the old
        # worker or the new one, never neither
        self._affinity: Dict[str, str] = {}
        self._rebinds = 0
        self._closed = False
        self._refresher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._naming_url = None
        from ..policy.naming import is_naming_url
        if isinstance(targets, str) and is_naming_url(targets):
            self._naming_url = targets
            self._refresh_interval_s = refresh_interval_s
            self._refresh_once()
            with self._lock:
                # fablint: thread-quiesced(close() sets _stop and joins; the poll loop checks it every interval)
                t = threading.Thread(target=self._refresh_loop,
                                     name="serving_router_refresh",
                                     daemon=True)
                self._refresher = t
            t.start()
        else:
            if isinstance(targets, str):
                targets = [t for t in targets.split(",") if t]
            for url in targets:
                self.add_target(url)

    # ---- membership ----------------------------------------------------
    def add_target(self, url: str) -> bool:
        return self._lb.add_server(parse_endpoint(url))

    def remove_target(self, url: str) -> bool:
        ep = parse_endpoint(url)
        ok = self._lb.remove_server(ep)
        with self._lock:
            ch = self._channels.pop(str(ep), None)
        if ch is not None:
            ch.close()
        return ok

    def targets(self) -> List[str]:
        return [str(e.endpoint) for e in self._lb.servers()]

    def _refresh_once(self) -> None:
        from ..policy.naming import create_naming_service
        try:
            entries = create_naming_service(self._naming_url).get_servers()
        except Exception:
            return
        fresh = {e.endpoint for e in entries}
        have = {e.endpoint for e in self._lb.servers()}
        for ep in fresh - have:
            self._lb.add_server(ep)
        for ep in have - fresh:
            self.remove_target(str(ep))

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self._refresh_interval_s):
            self._refresh_once()

    # ---- selection / feedback ------------------------------------------
    def pick(self, cntl=None,
             exclude: Optional[set] = None) -> Optional[str]:
        """Choose a decode worker by divided weight; ``exclude`` carries
        the endpoints a retry already burned."""
        if exclude:
            excl_eps = {parse_endpoint(u) for u in exclude}
            ep = None
            for _ in range(8):
                cand = self._lb.select_server(cntl)
                if cand is None or cand not in excl_eps:
                    ep = cand
                    break
                # a discarded draw must retire its AddInflight entry or
                # phantom in-flight accounting pins the worker's
                # divided weight at the floor after revival
                self._lb.cancel_inflight(cand)
            if ep is None:
                # the weighted draw kept landing on excluded workers:
                # a retry must still reach ANY remaining member, so
                # fall back to the membership list directly
                for e in self._lb.servers():
                    if e.endpoint not in excl_eps:
                        ep = e.endpoint
                        break
        else:
            ep = self._lb.select_server(cntl)
        if ep is None:
            return None
        url = str(ep)
        with self._lock:
            self._picks[url] = self._picks.get(url, 0) + 1
        return url

    def channel(self, url: str):
        from .. import rpc
        with self._lock:
            if self._closed:
                raise RuntimeError("router closed")
            ch = self._channels.get(url)
            if ch is None:
                ch = rpc.Channel()
                ch.init(url, options=self._copts)
                self._channels[url] = ch
            return ch

    def feedback(self, url: str, error_code: int,
                 latency_us: int) -> None:
        self._lb.feedback(parse_endpoint(url), error_code, latency_us)

    # ---- session affinity (ISSUE 19: the migration cutover flip) -------
    def bind_session(self, session: str, url: str) -> None:
        """Pin a live session to the decode worker holding its KV, so
        follow-up decodes (and a migration's cutover) route by session,
        not by weight."""
        with self._lock:
            while len(self._affinity) >= self.MAX_BOUND_SESSIONS:
                self._affinity.pop(next(iter(self._affinity)))
            self._affinity[session] = url

    def session_url(self, session: str) -> Optional[str]:
        with self._lock:
            return self._affinity.get(session)

    def rebind(self, session: str, url: str) -> Optional[str]:
        """The ATOMIC cutover: point a session's affinity at the
        migration destination.  Returns the previous binding (None if
        unbound) — the caller that owns the source copy uses it to
        release after the flip, never before."""
        with self._lock:
            prev = self._affinity.get(session)
            while session not in self._affinity \
                    and len(self._affinity) >= self.MAX_BOUND_SESSIONS:
                self._affinity.pop(next(iter(self._affinity)))
            self._affinity[session] = url
            if prev is not None and prev != url:
                self._rebinds += 1
            return prev

    def unbind(self, session: str) -> None:
        with self._lock:
            self._affinity.pop(session, None)

    # ---- lifecycle / observability --------------------------------------
    def close(self) -> None:
        self._stop.set()
        with self._lock:
            t = self._refresher
            self._refresher = None
            self._closed = True
            chans, self._channels = list(self._channels.values()), {}
        if t is not None:
            t.join(2.0)
        for ch in chans:
            ch.close()

    def describe(self) -> dict:
        """The /status serving block's routing half: divided weights +
        pick distribution per decode worker."""
        with self._lock:
            picks = dict(self._picks)
            bound = len(self._affinity)
            rebinds = self._rebinds
        weights = {}
        for e in self._lb.servers():
            weights[str(e.endpoint)] = round(
                self._lb.weight_of(e.endpoint), 1)
        return {"balancer": "la", "weights": weights, "picks": picks,
                "sessions_bound": bound, "rebinds": rebinds,
                "naming": self._naming_url or "static"}
