"""fablint: concurrency static analysis for the brpc_tpu package.

The fabric is deeply concurrent (ici/fabric.py alone holds 8 locks) and
every review pass of PRs 2-4 hand-caught the same bug classes: unguarded
shared state, lock-order inversions, blocking calls under a held lock,
and thread-owning objects with no quiesce path.  The reference ships
this as doctrine plus sanitizer builds (docs/en/io.md, TSan/ASan in its
CI); fablint is the machine-checkable half for the Python layer — the
moral equivalent of clang's thread-safety annotations
(``GUARDED_BY``/``EXCLUSIVE_LOCKS_REQUIRED``) for a codebase the clang
analyzer cannot see.

Passes (default command)
------------------------

``guarded-state``
    Attributes declared in a per-class ``_GUARDED_BY = {"_attr":
    "_lock"}`` map may only be read/written lexically inside ``with
    <base>.<lock>:`` where ``<base>`` is the same receiver (``self``,
    or e.g. ``peer`` for cross-object access), or inside a method
    marked ``# fablint: lock-held(<lock>)`` (callers hold it).
    ``__init__`` and methods marked ``# fablint: init`` are exempt
    (object not yet shared).  Module-level names declared in
    ``_GUARDED_BY_GLOBALS = {"_name": "_name_lock"}`` must be accessed
    inside ``with <lock>:`` from any function in that module.

``lock-order``
    Nested ``with``-lock acquisitions are extracted per module into a
    global acquisition graph; any cycle fails the lint.  Lock identity
    is ``Class.attr`` for ``self``/``cls`` locks, ``module:name`` for
    module-level locks (import aliases resolved), ``~attr`` for locks
    reached through another object.

``blocking-under-lock``
    Calls that can block the calling thread — ``.join()``,
    ``time.sleep``, socket ``recv``/``accept``/``connect``/
    ``create_connection``, ``subprocess.*``, jax ``device_put``/``jit``
    compilation, the coordination-service ``blocking_key_value_get`` —
    are flagged when they appear lexically inside a held-lock region.

``thread-hygiene``
    Every ``threading.Thread(...)`` spawn must pass ``daemon=True``
    AND have a quiesce path: either the thread handle is ``.join()``ed
    somewhere in the module, or the spawn carries a ``# fablint:
    thread-quiesced(<how>)`` marker naming its shutdown mechanism.
    This is the exact class behind the PR 2/4 exit-race flakes (static
    destructors racing live reader threads).

``plane-state``
    Per-plane health bookkeeping lives in ONE place
    (``ici/plane_health.py``) since ISSUE 17.  Any module OTHER than
    that file that (a) assigns a per-plane state field on ``self``/
    ``cls`` — ``_reestab_wanted``/``_running`` (plain or ``_shm_``-
    prefixed), ``_down``, ``_down_reason``, ``_down_epoch``,
    ``_down_at``, or any ``*_down_until`` latch — or (b) spawns a
    ``threading.Thread`` whose target name says revive/reestablish/
    reprobe, is growing a FIFTH hand-rolled health machine; the fix is
    ``plane_health.register_plane(...)`` with the plane keeping only
    its mechanics (dial, handshake payload, teardown).

Dead-code passes (``deadcode`` subcommand)
------------------------------------------

``dead-import``      imports never referenced in the module
                     (``__init__.py`` re-export modules are skipped;
                     ``# noqa`` honored).
``unreachable``      statements after return/raise/break/continue, and
                     ``if False:`` / ``while False:`` bodies.
``dead-global``      private (``_``-prefixed) module-level assignments
                     never read in their module and not in ``__all__``
                     (public names may be imported elsewhere, so only
                     private ones are provably dead).

Suppressions and markers
------------------------

``# fablint: ignore[rule1,rule2] <reason>``
    Suppresses those rules on that line.  The reason is REQUIRED —
    a reason-less ignore is itself reported (``bad-suppression``), so
    the accepted-findings baseline stays explicit and reviewed.
``# fablint: lock-held(_lock)``      method runs with self._lock held
``# fablint: init``                  constructor-path method, exempt
``# fablint: thread-quiesced(how)``  thread has a shutdown path

CLI
---

    python -m brpc_tpu.tools.fablint [paths...] [--json]
    python -m brpc_tpu.tools.fablint deadcode [paths...] [--json]
    python -m brpc_tpu.tools.fablint all [paths...] [--json]

Exit status 1 when findings exist, 0 when clean.  Default path: the
brpc_tpu package this module lives in.
"""
from __future__ import annotations

import ast
import io
import json
import os
import re
import sys
import tokenize
from typing import Dict, List, Optional, Set, Tuple

CONCURRENCY_RULES = ("guarded-state", "lock-order", "blocking-under-lock",
                     "thread-hygiene", "plane-state", "bad-suppression")
DEADCODE_RULES = ("dead-import", "unreachable", "dead-global")

# terminal callee names that can block the calling thread (pass 3).
# ``wait`` is deliberately absent: Condition.wait releases the lock it
# is called under, and butex waits park the tasklet, not the lock.
_BLOCKING_NAMES = {
    "sleep", "recv", "recvfrom", "recv_into", "accept", "connect",
    "create_connection", "device_put", "blocking_key_value_get",
    "jit", "getaddrinfo", "gethostbyname",
}
_SUBPROCESS_NAMES = {"run", "Popen", "check_output", "check_call", "call"}

_LOCKISH_RE = re.compile(r"lock|mutex", re.IGNORECASE)

# pass 5 (plane-state): the field names the four pre-ISSUE-17 health
# machines used — re-declaring one outside plane_health.py is the
# signature of a new hand-rolled machine, and the revival-thread regex
# catches the loop that always comes with it
_PLANE_STATE_RE = re.compile(
    r"^(?:_(?:shm_)?reestab_(?:wanted|running)|_down|_down_reason|"
    r"_down_epoch|_down_at|\w*_down_until)$")
_PLANE_THREAD_RE = re.compile(r"revive|reestab|reprobe", re.IGNORECASE)
_PLANE_HEALTH_BASENAME = "plane_health.py"

_DIRECTIVE_RE = re.compile(r"#\s*fablint:\s*(.*)$")
_IGNORE_RE = re.compile(r"ignore\[([\w\-, ]+)\]\s*(.*)$")
_LOCK_HELD_RE = re.compile(r"lock-held\(([\w, ]+)\)")
_THREAD_QUIESCED_RE = re.compile(r"thread-quiesced\(([^)]*)\)")
_INIT_RE = re.compile(r"\binit\b")


class Finding:
    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class _Directives:
    """Per-module comment directives, keyed by line number."""

    def __init__(self, source: str, path: str):
        self.ignores: Dict[int, Tuple[Set[str], str]] = {}
        self.lock_held: Dict[int, List[str]] = {}
        self.init_marks: Set[int] = set()
        self.thread_quiesced: Dict[int, str] = {}
        self.noqa: Set[int] = set()
        self.bad: List[Tuple[int, str]] = []     # reason-less ignores etc.
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                line = tok.start[0]
                text = tok.string
                if "noqa" in text:
                    self.noqa.add(line)
                m = _DIRECTIVE_RE.search(text)
                if not m:
                    continue
                body = m.group(1).strip()
                im = _IGNORE_RE.match(body)
                if im:
                    rules = {r.strip() for r in im.group(1).split(",")
                             if r.strip()}
                    reason = im.group(2).strip()
                    if not reason:
                        self.bad.append(
                            (line, "ignore[] without a reason — every "
                                   "suppression must say why"))
                    self.ignores[line] = (rules, reason)
                    continue
                lm = _LOCK_HELD_RE.match(body)
                if lm:
                    self.lock_held[line] = [x.strip() for x in
                                            lm.group(1).split(",") if x.strip()]
                    continue
                tm = _THREAD_QUIESCED_RE.match(body)
                if tm:
                    self.thread_quiesced[line] = tm.group(1).strip()
                    continue
                if _INIT_RE.match(body):
                    self.init_marks.add(line)
                    continue
                self.bad.append((line, f"unknown fablint directive: {body!r}"))
        except tokenize.TokenError:
            pass

    def suppressed(self, rule: str, line: int) -> bool:
        ent = self.ignores.get(line)
        return ent is not None and (rule in ent[0] or "all" in ent[0])

    def _def_marker(self, table, node):
        """A def-attached marker sits on the def line or the line above
        (above a decorator counts too)."""
        first = min([node.lineno] + [d.lineno for d in
                    getattr(node, "decorator_list", [])])
        for ln in (node.lineno, first - 1, node.lineno - 1):
            if ln in table:
                return table[ln]
        return None

    def fn_lock_held(self, node) -> List[str]:
        return self._def_marker(self.lock_held, node) or []

    def fn_is_init(self, node) -> bool:
        first = min([node.lineno] + [d.lineno for d in
                    getattr(node, "decorator_list", [])])
        return bool({node.lineno, first - 1, node.lineno - 1}
                    & self.init_marks)

    def thread_marker(self, lineno: int) -> Optional[str]:
        for ln in (lineno, lineno - 1):
            if ln in self.thread_quiesced:
                return self.thread_quiesced[ln]
        return None


def _literal_str_dict(node: ast.AST) -> Optional[Dict[str, str]]:
    if not isinstance(node, ast.Dict):
        return None
    out = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Constant) and isinstance(v.value, str)):
            return None
        out[k.value] = v.value
    return out


class _Held:
    """One lexically-held lock: (receiver base name or None for a
    module-level lock, lock name, canonical graph identity)."""

    __slots__ = ("base", "name", "canonical")

    def __init__(self, base: Optional[str], name: str, canonical: str):
        self.base = base
        self.name = name
        self.canonical = canonical


class ModuleLint:
    """All passes over one module; lock-order edges are merged globally
    by the driver."""

    def __init__(self, path: str, source: str, modname: str):
        self.path = path
        self.source = source
        self.modname = modname
        self.tree = ast.parse(source, filename=path)
        self.directives = _Directives(source, path)
        self.findings: List[Finding] = []
        # canonical lock id -> {canonical lock id -> (path, line)}
        self.lock_edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
        self.import_aliases = self._collect_import_aliases()
        self.class_guards = self._collect_class_guards()
        self.global_guards = self._collect_global_guards()
        self._known_locks = set(self.global_guards.values())
        for g in self.class_guards.values():
            self._known_locks.update(g.values())

    # ---- collection -----------------------------------------------------
    def _collect_import_aliases(self) -> Dict[str, str]:
        """Bound name -> 'resolved.module:orig' for from-imports, so a
        module-level lock imported under an alias keeps one identity."""
        out: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module is not None:
                mod = node.module
                if node.level:
                    parts = self.modname.split(".")
                    base = parts[:max(len(parts) - node.level, 0)]
                    mod = ".".join(base + [node.module])
                for alias in node.names:
                    out[alias.asname or alias.name] = f"{mod}:{alias.name}"
        return out

    def _collect_class_guards(self) -> Dict[str, Dict[str, str]]:
        out: Dict[str, Dict[str, str]] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and stmt.targets[0].id == "_GUARDED_BY"):
                    d = _literal_str_dict(stmt.value)
                    if d is None:
                        self._report("guarded-state", stmt.lineno,
                                     "_GUARDED_BY must be a literal "
                                     "{str: str} dict")
                    else:
                        out[node.name] = d
        return out

    def _collect_global_guards(self) -> Dict[str, str]:
        for stmt in self.tree.body:
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "_GUARDED_BY_GLOBALS"):
                d = _literal_str_dict(stmt.value)
                if d is None:
                    self._report("guarded-state", stmt.lineno,
                                 "_GUARDED_BY_GLOBALS must be a literal "
                                 "{str: str} dict")
                    return {}
                return d
        return {}

    # ---- reporting ------------------------------------------------------
    def _report(self, rule: str, line: int, message: str) -> None:
        if self.directives.suppressed(rule, line):
            return
        self.findings.append(Finding(rule, self.path, line, message))

    # ---- lock identity --------------------------------------------------
    def _lockish(self, expr: ast.AST) -> Optional[Tuple[Optional[str], str]]:
        """(base name or None, lock name) when ``expr`` looks like a
        lock; None otherwise.  Calls (``self._dbd.read()``) never are."""
        if isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                            ast.Name):
            name = expr.attr
        else:
            return None
        if not (_LOCKISH_RE.search(name) or name in self._known_locks):
            return None
        if isinstance(expr, ast.Name):
            return (None, name)
        return (expr.value.id, name)

    def _canonical(self, base: Optional[str], name: str,
                   class_name: Optional[str]) -> str:
        if base is None:
            return self.import_aliases.get(name, f"{self.modname}:{name}")
        if base in ("self", "cls") and class_name:
            return f"{class_name}.{name}"
        return f"~{name}"

    # ---- the concurrency walk -------------------------------------------
    def run_concurrency(self) -> None:
        for line, msg in self.directives.bad:
            self.findings.append(
                Finding("bad-suppression", self.path, line, msg))
        self._walk_body(self.tree.body, held=[], class_name=None,
                        fn_node=None, guard_exempt=True)

    def _walk_body(self, body, held, class_name, fn_node, guard_exempt):
        for stmt in body:
            self._walk_stmt(stmt, held, class_name, fn_node, guard_exempt)

    def _walk_stmt(self, node, held, class_name, fn_node, guard_exempt):
        if isinstance(node, ast.ClassDef):
            # class body executes at import (single-threaded): exempt
            self._walk_body(node.body, [], node.name, None, True)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs LATER: locks lexically held around it
            # are not held when it executes — reset the held set
            seeded: List[_Held] = []
            for lock in self.directives.fn_lock_held(node):
                seeded.append(_Held("self", lock,
                                    self._canonical("self", lock,
                                                    class_name)))
            exempt = (class_name is not None and fn_node is None
                      and node.name == "__init__") \
                or self.directives.fn_is_init(node)
            self._walk_body(node.body, seeded, class_name, node, exempt)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in node.items:
                lk = self._lockish(item.context_expr)
                if lk is None:
                    self._visit_exprs(item.context_expr, held, class_name,
                                      fn_node, guard_exempt)
                    continue
                base, name = lk
                canon = self._canonical(base, name, class_name)
                if held and not self.directives.suppressed(
                        "lock-order", node.lineno):
                    outer = held[-1].canonical
                    if outer != canon:
                        self.lock_edges.setdefault(outer, {}) \
                            .setdefault(canon, (self.path, node.lineno))
                held.append(_Held(base, name, canon))
                pushed += 1
            self._walk_body(node.body, held, class_name, fn_node,
                            guard_exempt)
            for _ in range(pushed):
                held.pop()
            return
        # generic statement: visit expressions, recurse into sub-bodies
        for field in ("test", "iter", "value", "targets", "target", "exc",
                      "cause", "msg", "items", "subject"):
            sub = getattr(node, field, None)
            if sub is None:
                continue
            for expr in (sub if isinstance(sub, list) else [sub]):
                if isinstance(expr, ast.AST):
                    self._visit_exprs(expr, held, class_name, fn_node,
                                      guard_exempt)
        for field in ("body", "orelse", "finalbody", "handlers", "cases"):
            sub = getattr(node, field, None)
            if not sub:
                continue
            for child in sub:
                if isinstance(child, (ast.ExceptHandler, ast.match_case)):
                    self._walk_body(child.body, held, class_name, fn_node,
                                    guard_exempt)
                elif isinstance(child, ast.AST):
                    self._walk_stmt(child, held, class_name, fn_node,
                                    guard_exempt)

    def _visit_exprs(self, expr, held, class_name, fn_node, guard_exempt):
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                self._check_attr_access(node, held, class_name, fn_node,
                                        guard_exempt)
                self._check_plane_state_attr(node)
            elif isinstance(node, ast.Name):
                self._check_global_access(node, held, fn_node, guard_exempt)
            elif isinstance(node, ast.Call):
                self._check_blocking(node, held)
                self._check_thread_spawn(node)
                self._check_plane_state_thread(node)
            elif isinstance(node, (ast.Lambda,)):
                pass        # lambdas run later; their bodies are tiny and
                # attribute checks inside would be against a reset held
                # set — handled conservatively by not descending
                # (ast.walk descends anyway; accesses in lambdas are
                # checked against the ENCLOSING held set, a known
                # imprecision kept for simplicity)

    # ---- pass 1: guarded state -----------------------------------------
    def _check_attr_access(self, node: ast.Attribute, held, class_name,
                           fn_node, guard_exempt) -> None:
        if guard_exempt or class_name is None or fn_node is None:
            return
        guards = self.class_guards.get(class_name)
        if not guards or node.attr not in guards:
            return
        if not isinstance(node.value, ast.Name):
            return
        base = node.value.id
        need = guards[node.attr]
        for h in held:
            # a held module-level lock of the declared name also
            # satisfies (instance state guarded by a registry lock —
            # the health-check pattern)
            if h.name == need and (h.base == base or h.base is None):
                return
        if base == "self":
            if need in self.directives.fn_lock_held(fn_node):
                return
        self._report(
            "guarded-state", node.lineno,
            f"{class_name}.{fn_node.name}: access to {base}.{node.attr} "
            f"outside 'with {base}.{need}:' (declared in _GUARDED_BY)")

    def _check_global_access(self, node: ast.Name, held, fn_node,
                             guard_exempt) -> None:
        if guard_exempt or fn_node is None:
            return
        need = self.global_guards.get(node.id)
        if need is None:
            return
        for h in held:
            if h.base is None and h.name == need:
                return
        if need in self.directives.fn_lock_held(fn_node):
            return
        self._report(
            "guarded-state", node.lineno,
            f"{fn_node.name}: access to module global {node.id} outside "
            f"'with {need}:' (declared in _GUARDED_BY_GLOBALS)")

    # ---- pass 3: blocking under lock -----------------------------------
    def _check_blocking(self, node: ast.Call, held) -> None:
        if not held:
            return
        func = node.func
        name = None
        base = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
            base = func.value
        if name is None:
            return
        blocking = False
        if name in _BLOCKING_NAMES:
            blocking = True
        elif name in _SUBPROCESS_NAMES and isinstance(base, ast.Name) \
                and base.id == "subprocess":
            blocking = True
        elif name == "join":
            # distinguish thread.join(timeout?) from str.join(iterable):
            # a str/bytes receiver, or a single non-numeric argument,
            # is string joining
            if isinstance(base, ast.Constant) and isinstance(
                    base.value, (str, bytes)):
                blocking = False
            elif len(node.args) == 0 and not node.keywords:
                blocking = True
            elif (len(node.args) == 1 and not node.keywords
                  and isinstance(node.args[0], ast.Constant)
                  and isinstance(node.args[0].value, (int, float))):
                blocking = True
            elif any(kw.arg == "timeout" for kw in node.keywords):
                blocking = True
        if not blocking:
            return
        locks = ", ".join(h.name for h in held)
        self._report(
            "blocking-under-lock", node.lineno,
            f"call to blocking '{name}' while holding {locks}")

    # ---- pass 4: thread hygiene ----------------------------------------
    def _check_thread_spawn(self, node: ast.Call) -> None:
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if name != "Thread":
            return
        daemon = None
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = kw.value.value
        joined = self._thread_provably_joined(node)
        if daemon is not True and not joined:
            # a thread that is synchronously joined may be non-daemon
            # (CLI worker fan-outs); anything else must not block exit
            self._report(
                "thread-hygiene", node.lineno,
                "threading.Thread spawned without daemon=True and not "
                "provably joined — a non-daemon thread blocks "
                "interpreter exit and races static teardown")
        if joined or self.directives.thread_marker(node.lineno):
            return
        self._report(
            "thread-hygiene", node.lineno,
            "thread has no visible quiesce path: no .join() on its "
            "handle in this module and no '# fablint: "
            "thread-quiesced(<how>)' marker")

    def _thread_provably_joined(self, node: ast.Call) -> bool:
        """Some name transitively holding the spawned thread is
        .join()ed somewhere in this module.  Aliases are chased through
        assignments (``t = Thread(...)``, ``self._r = t``, ``r, self._r
        = self._r, None``) and for-loops over a holding list (``for t
        in threads: t.join()``) — a weak but honest lexical proof."""

        def tname(t):
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Attribute):
                return t.attr
            return None

        assigns = [n for n in ast.walk(self.tree)
                   if isinstance(n, ast.Assign)]
        names: Set[str] = set()
        for a in assigns:
            if any(sub is node for sub in ast.walk(a.value)):
                for t in a.targets:
                    n = tname(t)
                    if n:
                        names.add(n)
        if not names:
            return False
        changed = True
        while changed:
            changed = False
            for a in assigns:
                if (len(a.targets) == 1
                        and isinstance(a.targets[0], ast.Tuple)
                        and isinstance(a.value, ast.Tuple)
                        and len(a.targets[0].elts) == len(a.value.elts)):
                    pairs = list(zip(a.targets[0].elts, a.value.elts))
                else:
                    pairs = [(t, a.value) for t in a.targets]
                for t, v in pairs:
                    vn = tname(v) if isinstance(
                        v, (ast.Name, ast.Attribute)) else None
                    tn = tname(t)
                    if vn in names and tn and tn not in names:
                        names.add(tn)
                        changed = True
            for n in ast.walk(self.tree):
                if isinstance(n, ast.For) and isinstance(n.iter, ast.Name) \
                        and n.iter.id in names \
                        and isinstance(n.target, ast.Name) \
                        and n.target.id not in names:
                    names.add(n.target.id)
                    changed = True
        return any(
            re.search(r"\b%s\s*\.\s*join\s*\(" % re.escape(nm), self.source)
            for nm in names)

    # ---- pass 5: plane-state containment --------------------------------
    def _check_plane_state_attr(self, node: ast.Attribute) -> None:
        if os.path.basename(self.path) == _PLANE_HEALTH_BASENAME:
            return
        if not isinstance(node.ctx, (ast.Store, ast.Del)):
            return
        if not (isinstance(node.value, ast.Name)
                and node.value.id in ("self", "cls")):
            return
        if not _PLANE_STATE_RE.match(node.attr):
            return
        self._report(
            "plane-state", node.lineno,
            f"per-plane health state field '{node.attr}' declared outside "
            f"ici/plane_health.py — register the plane with "
            f"plane_health.register_plane() instead of growing a private "
            f"down/reestablish machine")

    def _check_plane_state_thread(self, node: ast.Call) -> None:
        if os.path.basename(self.path) == _PLANE_HEALTH_BASENAME:
            return
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if name != "Thread":
            return
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            tgt = v.attr if isinstance(v, ast.Attribute) else (
                v.id if isinstance(v, ast.Name) else "")
            if _PLANE_THREAD_RE.search(tgt):
                self._report(
                    "plane-state", node.lineno,
                    f"revival thread (target '{tgt}') spawned outside "
                    f"ici/plane_health.py — the engine owns every plane's "
                    f"revival loop; planes supply only a prober callback")

    # ---- dead-code passes ----------------------------------------------
    def run_deadcode(self) -> None:
        self._dead_imports()
        self._unreachable()
        self._dead_globals()

    def _used_names(self) -> Set[str]:
        used: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                # x.y.z — count the root name (handled by Name Load) and
                # string re-exports via __all__ below
                pass
        for stmt in self.tree.body:
            if (isinstance(stmt, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "__all__"
                            for t in stmt.targets)
                    and isinstance(stmt.value, (ast.List, ast.Tuple))):
                for elt in stmt.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str):
                        used.add(elt.value)
        return used

    def _dead_imports(self) -> None:
        if os.path.basename(self.path) == "__init__.py":
            return              # re-export modules: imports ARE the API
        used = self._used_names()
        for node in ast.walk(self.tree):
            aliases = []
            if isinstance(node, ast.Import):
                aliases = node.names
            elif isinstance(node, ast.ImportFrom):
                aliases = node.names
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "__future__":
                continue
            for alias in aliases:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name.split(".")[0]
                if bound in used:
                    continue
                if node.lineno in self.directives.noqa:
                    continue
                self._report("dead-import", node.lineno,
                             f"'{bound}' imported but never used")

    def _unreachable(self) -> None:
        for node in ast.walk(self.tree):
            for field in ("body", "orelse", "finalbody"):
                body = getattr(node, field, None)
                if not isinstance(body, list):
                    continue
                terminated = False
                for stmt in body:
                    if terminated:
                        self._report("unreachable", stmt.lineno,
                                     "statement is unreachable (follows "
                                     "return/raise/break/continue)")
                        break
                    if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                         ast.Continue)):
                        terminated = True
            if isinstance(node, (ast.If, ast.While)) and isinstance(
                    node.test, ast.Constant) and node.test.value is False:
                self._report("unreachable", node.lineno,
                             "branch condition is literally False")

    def _dead_globals(self) -> None:
        used = self._used_names()
        stores: Dict[str, int] = {}
        for stmt in self.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    stores.setdefault(t.id, stmt.lineno)
        for name, line in sorted(stores.items(), key=lambda kv: kv[1]):
            if not name.startswith("_") or name.startswith("__"):
                continue        # public names may be imported elsewhere
            if name in used or name in ("_GUARDED_BY_GLOBALS",):
                continue
            if line in self.directives.noqa:
                continue
            self._report("dead-global", line,
                         f"module-level private name '{name}' is written "
                         f"but never read in this module")


# ---- driver -------------------------------------------------------------

def _iter_py_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for f in sorted(files):
                    if f.endswith(".py") and not f.endswith("_pb2.py"):
                        out.append(os.path.join(root, f))   # _pb2: generated
        elif p.endswith(".py"):
            out.append(p)
    return out


def _modname_for(path: str) -> str:
    """Dotted module name: walk up while __init__.py exists."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    name = ".".join(reversed(parts))
    return name[:-len(".__init__")] if name.endswith(".__init__") else name


def _find_cycles(graph: Dict[str, Dict[str, Tuple[str, int]]]
                 ) -> List[List[str]]:
    """Cycles in the acquisition digraph (one representative per SCC
    with a cycle), via iterative Tarjan."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v0: str) -> None:
        work = [(v0, iter(graph.get(v0, ())))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on_stack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(graph.get(w, ()))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1 or v in graph.get(v, ()):
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def run(paths: List[str], rules: Tuple[str, ...]) -> List[Finding]:
    findings: List[Finding] = []
    edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
    want_conc = any(r in rules for r in CONCURRENCY_RULES)
    want_dead = any(r in rules for r in DEADCODE_RULES)
    for path in _iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            lint = ModuleLint(path, source, _modname_for(path))
        except SyntaxError as e:
            findings.append(Finding("parse-error", path, e.lineno or 0,
                                    str(e)))
            continue
        if want_conc:
            lint.run_concurrency()
        if want_dead:
            lint.run_deadcode()
        findings.extend(f for f in lint.findings if f.rule in rules
                        or f.rule == "parse-error")
        for src, dsts in lint.lock_edges.items():
            for dst, loc in dsts.items():
                edges.setdefault(src, {}).setdefault(dst, loc)
    if "lock-order" in rules:
        for comp in _find_cycles(edges):
            locs = []
            for a in comp:
                for b, (p, ln) in edges.get(a, {}).items():
                    if b in comp:
                        locs.append(f"{a} -> {b} at {p}:{ln}")
            first = edges[comp[0]]
            path0, line0 = next(iter(first.values()))
            findings.append(Finding(
                "lock-order", path0, line0,
                "lock acquisition cycle: " + "; ".join(sorted(locs))))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lock_order_edges(paths: List[str]
                     ) -> Dict[str, Dict[str, Tuple[str, int]]]:
    """The extracted acquisition graph (docs/CONCURRENCY.md generator)."""
    edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
    for path in _iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            lint = ModuleLint(path, source, _modname_for(path))
        except SyntaxError:
            continue
        lint.run_concurrency()
        for src, dsts in lint.lock_edges.items():
            for dst, loc in dsts.items():
                edges.setdefault(src, {}).setdefault(dst, loc)
    return edges


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    cmd = "check"
    if argv and argv[0] in ("check", "deadcode", "all"):
        cmd = argv.pop(0)
    paths = argv or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    rules = {"check": CONCURRENCY_RULES,
             "deadcode": DEADCODE_RULES,
             "all": CONCURRENCY_RULES + DEADCODE_RULES}[cmd]
    findings = run(paths, rules)
    if as_json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        print(f"fablint: {len(findings)} finding(s) "
              f"[{cmd}] over {len(_iter_py_files(paths))} file(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
