"""fablint: concurrency static analysis for the brpc_tpu package.

The fabric is deeply concurrent (ici/fabric.py alone holds 8 locks) and
every review pass of PRs 2-4 hand-caught the same bug classes: unguarded
shared state, lock-order inversions, blocking calls under a held lock,
and thread-owning objects with no quiesce path.  The reference ships
this as doctrine plus sanitizer builds (docs/en/io.md, TSan/ASan in its
CI); fablint is the machine-checkable half for the Python layer — the
moral equivalent of clang's thread-safety annotations
(``GUARDED_BY``/``EXCLUSIVE_LOCKS_REQUIRED``) for a codebase the clang
analyzer cannot see.

Passes (default command)
------------------------

``guarded-state``
    Attributes declared in a per-class ``_GUARDED_BY = {"_attr":
    "_lock"}`` map may only be read/written lexically inside ``with
    <base>.<lock>:`` where ``<base>`` is the same receiver (``self``,
    or e.g. ``peer`` for cross-object access), or inside a method
    marked ``# fablint: lock-held(<lock>)`` (callers hold it).
    ``__init__`` and methods marked ``# fablint: init`` are exempt
    (object not yet shared).  Module-level names declared in
    ``_GUARDED_BY_GLOBALS = {"_name": "_name_lock"}`` must be accessed
    inside ``with <lock>:`` from any function in that module.

``lock-order``
    Nested ``with``-lock acquisitions are extracted per module into a
    global acquisition graph; any cycle fails the lint.  Lock identity
    is ``Class.attr`` for ``self``/``cls`` locks, ``module:name`` for
    module-level locks (import aliases resolved), ``~attr`` for locks
    reached through another object.

``blocking-under-lock``
    Calls that can block the calling thread — ``.join()``,
    ``time.sleep``, socket ``recv``/``accept``/``connect``/
    ``create_connection``, ``subprocess.*``, jax ``device_put``/``jit``
    compilation, the coordination-service ``blocking_key_value_get`` —
    are flagged when they appear lexically inside a held-lock region.

``thread-hygiene``
    Every ``threading.Thread(...)`` spawn must pass ``daemon=True``
    AND have a quiesce path: either the thread handle is ``.join()``ed
    somewhere in the module, or the spawn carries a ``# fablint:
    thread-quiesced(<how>)`` marker naming its shutdown mechanism.
    This is the exact class behind the PR 2/4 exit-race flakes (static
    destructors racing live reader threads).

``plane-state``
    Per-plane health bookkeeping lives in ONE place
    (``ici/plane_health.py``) since ISSUE 17.  Any module OTHER than
    that file that (a) assigns a per-plane state field on ``self``/
    ``cls`` — ``_reestab_wanted``/``_running`` (plain or ``_shm_``-
    prefixed), ``_down``, ``_down_reason``, ``_down_epoch``,
    ``_down_at``, or any ``*_down_until`` latch — or (b) spawns a
    ``threading.Thread`` whose target name says revive/reestablish/
    reprobe, is growing a FIFTH hand-rolled health machine; the fix is
    ``plane_health.register_plane(...)`` with the plane keeping only
    its mechanics (dial, handshake payload, teardown).

Custody passes (``custody`` subcommand, ISSUE 20)
-------------------------------------------------

The reference's correctness doctrine is custody discipline: Socket
fails exactly once, resource_pool hands out versioned ids, every pin /
parked handle has exactly one exit.  Custody-carrying classes (or
modules) declare their protocol::

    _CUSTODY = {"pin": ("unpin",),                  # acquire method
                "_refs": ("_free_session_locked",),  # refcount field
                }

``custody``
    Path-sensitive acquire/release: every lexical acquisition — a
    declared acquire call (``pool.pin(s)``, ``blocks, old =
    self._reserve_locked(...)``, ``if not pool.pin(s): return``), or a
    ``+= 1`` on a declared refcount field — must reach, on every exit
    path INCLUDING exception edges, one of: a matching release, an
    explicit transfer marked ``# fablint: custody-moved(<to>)
    <reason>``, or a return of the owning object.  A statement that can
    raise while custody is held must sit under a ``try`` whose broad
    handler or ``finally`` releases.  The analysis is lexical and
    per-function: class declarations match ``self`` receivers inside
    the declaring class plus receivers whose name shares a token with
    the class name (``pool.pin`` matches ``PagedKvPool``); module-level
    ``_CUSTODY`` maps match only their own module.  Known benign calls
    (builtins, container methods) are not exception edges.

``refcount-balance``
    Every ``±1`` on a declared refcount field must sit under the
    field's ``_GUARDED_BY`` lock (any held lock if undeclared, or a
    ``lock-held`` marker), and every decrement site must dominate a
    zero-check that frees — ``r = refs.get(b, 1) - 1`` followed by
    ``if r <= 0: refs.pop(...)``, a decrement under an
    ``if refs.get(b, 1) > 1:`` guard, or ``x -= 1`` followed by an
    ``if not x ...: free()`` — or carry a reasoned suppression.

The runtime complement is ``butil/custody_ledger.py`` (``debug_custody``
flag): declared acquire/release points record stack-tagged ledger
entries, so a leak that rides a ``custody-moved`` transfer whose far
end never fires is attributed to its acquiring file:line at runtime.

Dead-code passes (``deadcode`` subcommand)
------------------------------------------

``dead-import``      imports never referenced in the module
                     (``__init__.py`` re-export modules are skipped;
                     ``# noqa`` honored).
``unreachable``      statements after return/raise/break/continue, and
                     ``if False:`` / ``while False:`` bodies.
``dead-global``      private (``_``-prefixed) module-level assignments
                     never read in their module and not in ``__all__``
                     (public names may be imported elsewhere, so only
                     private ones are provably dead).

Suppressions and markers
------------------------

``# fablint: ignore[rule1,rule2] <reason>``
    Suppresses those rules on that line.  The reason is REQUIRED —
    a reason-less ignore is itself reported (``bad-suppression``), so
    the accepted-findings baseline stays explicit and reviewed.
``# fablint: lock-held(_lock)``      method runs with self._lock held
``# fablint: init``                  constructor-path method, exempt
``# fablint: thread-quiesced(how)``  thread has a shutdown path
``# fablint: custody-moved(to) why`` ownership transferred to <to>; the
                                     reason is REQUIRED, like ignore[]

CLI
---

    python -m brpc_tpu.tools.fablint [paths...] [--json]
    python -m brpc_tpu.tools.fablint deadcode [paths...] [--json]
    python -m brpc_tpu.tools.fablint custody [paths...] [--json]
    python -m brpc_tpu.tools.fablint all [paths...] [--json]
    python -m brpc_tpu.tools.fablint all --rules custody,lock-order ...

``--rules a,b`` restricts any command to the named rules (CI bisection:
a new rule can be vetted without muting the rest).  Exit status 1 when
findings exist, 0 when clean.  Default path: the brpc_tpu package this
module lives in.
"""
from __future__ import annotations

import ast
import io
import json
import os
import re
import sys
import tokenize
from typing import Dict, List, Optional, Set, Tuple

CONCURRENCY_RULES = ("guarded-state", "lock-order", "blocking-under-lock",
                     "thread-hygiene", "plane-state", "bad-suppression")
CUSTODY_RULES = ("custody", "refcount-balance")
DEADCODE_RULES = ("dead-import", "unreachable", "dead-global")
ALL_RULES = CONCURRENCY_RULES + CUSTODY_RULES + DEADCODE_RULES

# terminal callee names that can block the calling thread (pass 3).
# ``wait`` is deliberately absent: Condition.wait releases the lock it
# is called under, and butex waits park the tasklet, not the lock.
_BLOCKING_NAMES = {
    "sleep", "recv", "recvfrom", "recv_into", "accept", "connect",
    "create_connection", "device_put", "blocking_key_value_get",
    "jit", "getaddrinfo", "gethostbyname",
}
_SUBPROCESS_NAMES = {"run", "Popen", "check_output", "check_call", "call"}

# large-copy callees (ISSUE 20 satellite): a block-sized tobytes /
# copyto / array_equal under a held lock serializes every other holder
# behind a memcpy — the PR-19 demote-copy debt class.  Accepted sites
# carry reasoned suppressions so the debt stays visible in-tree.
_LARGE_COPY_NAMES = {"tobytes", "copyto", "array_equal"}

_LOCKISH_RE = re.compile(r"lock|mutex", re.IGNORECASE)

# custody pass: calls that are not exception edges for the lexical
# acquire/release analysis — builtins and container/dict methods whose
# failure modes (MemoryError, a KeyError on a missing key the code
# just checked) are interpreter-level, not resource-path-level.  A
# deliberately small list: anything else that can raise between an
# acquire and its release needs try coverage.
_BENIGN_CALLS = {
    "range", "len", "enumerate", "zip", "int", "float", "str", "bool",
    "bytes", "min", "max", "abs", "list", "tuple", "set", "dict",
    "sorted", "reversed", "isinstance", "getattr", "hasattr", "id",
    "iter", "next", "repr", "bin",
}
_BENIGN_METHODS = {
    "get", "pop", "popleft", "append", "appendleft", "add", "discard",
    "remove", "extend", "sort", "setdefault", "update", "clear",
    "items", "keys", "values", "copy",
}
# receivers whose method calls are edge-benign: the runtime custody
# ledger's own hooks are no-op instrumentation (flag-gated early-out),
# never a raise site between an acquire and its release
_BENIGN_ROOTS = {"_ledger", "custody_ledger"}
_BROAD_EXC_NAMES = {"Exception", "BaseException"}
_FREEISH_RE = re.compile(
    r"free|pop|release|unregister|return|evict|clear|discard|del",
    re.IGNORECASE)

# pass 5 (plane-state): the field names the four pre-ISSUE-17 health
# machines used — re-declaring one outside plane_health.py is the
# signature of a new hand-rolled machine, and the revival-thread regex
# catches the loop that always comes with it
_PLANE_STATE_RE = re.compile(
    r"^(?:_(?:shm_)?reestab_(?:wanted|running)|_down|_down_reason|"
    r"_down_epoch|_down_at|\w*_down_until)$")
_PLANE_THREAD_RE = re.compile(r"revive|reestab|reprobe", re.IGNORECASE)
_PLANE_HEALTH_BASENAME = "plane_health.py"

_DIRECTIVE_RE = re.compile(r"#\s*fablint:\s*(.*)$")
_IGNORE_RE = re.compile(r"ignore\[([\w\-, ]+)\]\s*(.*)$")
_LOCK_HELD_RE = re.compile(r"lock-held\(([\w, ]+)\)")
_THREAD_QUIESCED_RE = re.compile(r"thread-quiesced\(([^)]*)\)")
_CUSTODY_MOVED_RE = re.compile(r"custody-moved\(([^)]*)\)\s*(.*)$")
_INIT_RE = re.compile(r"\binit\b")


class Finding:
    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class _Directives:
    """Per-module comment directives, keyed by line number."""

    def __init__(self, source: str, path: str):
        self.ignores: Dict[int, Tuple[Set[str], str]] = {}
        self.lock_held: Dict[int, List[str]] = {}
        self.init_marks: Set[int] = set()
        self.thread_quiesced: Dict[int, str] = {}
        self.custody_moved: Dict[int, Tuple[str, str]] = {}   # (to, why)
        self.noqa: Set[int] = set()
        self.bad: List[Tuple[int, str]] = []     # reason-less ignores etc.
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                line = tok.start[0]
                text = tok.string
                if "noqa" in text:
                    self.noqa.add(line)
                m = _DIRECTIVE_RE.search(text)
                if not m:
                    continue
                body = m.group(1).strip()
                im = _IGNORE_RE.match(body)
                if im:
                    rules = {r.strip() for r in im.group(1).split(",")
                             if r.strip()}
                    reason = im.group(2).strip()
                    if not reason:
                        self.bad.append(
                            (line, "ignore[] without a reason — every "
                                   "suppression must say why"))
                    self.ignores[line] = (rules, reason)
                    continue
                lm = _LOCK_HELD_RE.match(body)
                if lm:
                    self.lock_held[line] = [x.strip() for x in
                                            lm.group(1).split(",") if x.strip()]
                    continue
                tm = _THREAD_QUIESCED_RE.match(body)
                if tm:
                    self.thread_quiesced[line] = tm.group(1).strip()
                    continue
                cm = _CUSTODY_MOVED_RE.match(body)
                if cm:
                    to = cm.group(1).strip()
                    why = cm.group(2).strip()
                    if not why:
                        self.bad.append(
                            (line, "custody-moved() without a reason — "
                                   "every ownership transfer must say "
                                   "who releases and why"))
                    self.custody_moved[line] = (to, why)
                    continue
                if _INIT_RE.match(body):
                    self.init_marks.add(line)
                    continue
                self.bad.append((line, f"unknown fablint directive: {body!r}"))
        except tokenize.TokenError:
            pass

    def suppressed(self, rule: str, line: int) -> bool:
        ent = self.ignores.get(line)
        return ent is not None and (rule in ent[0] or "all" in ent[0])

    def _def_marker(self, table, node):
        """A def-attached marker sits on the def line or the line above
        (above a decorator counts too)."""
        first = min([node.lineno] + [d.lineno for d in
                    getattr(node, "decorator_list", [])])
        for ln in (node.lineno, first - 1, node.lineno - 1):
            if ln in table:
                return table[ln]
        return None

    def fn_lock_held(self, node) -> List[str]:
        return self._def_marker(self.lock_held, node) or []

    def fn_is_init(self, node) -> bool:
        first = min([node.lineno] + [d.lineno for d in
                    getattr(node, "decorator_list", [])])
        return bool({node.lineno, first - 1, node.lineno - 1}
                    & self.init_marks)

    def thread_marker(self, lineno: int) -> Optional[str]:
        for ln in (lineno, lineno - 1):
            if ln in self.thread_quiesced:
                return self.thread_quiesced[ln]
        return None

    def moved_marker(self, *linenos: int) -> Optional[Tuple[str, str]]:
        """custody-moved on any of the given lines or the line above
        the first (multi-line acquire statements put the marker where
        it fits)."""
        cands = list(linenos) + [linenos[0] - 1] if linenos else []
        for ln in cands:
            if ln in self.custody_moved:
                return self.custody_moved[ln]
        return None


def _literal_str_dict(node: ast.AST) -> Optional[Dict[str, str]]:
    if not isinstance(node, ast.Dict):
        return None
    out = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Constant) and isinstance(v.value, str)):
            return None
        out[k.value] = v.value
    return out


def _literal_custody_dict(node: ast.AST
                          ) -> Optional[Dict[str, Tuple[str, ...]]]:
    """``_CUSTODY = {"acquire": ("rel_a", "rel_b")}`` — keys str,
    values tuple/list of str."""
    if not isinstance(node, ast.Dict):
        return None
    out: Dict[str, Tuple[str, ...]] = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, (ast.Tuple, ast.List))):
            return None
        rels = []
        for elt in v.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            rels.append(elt.value)
        out[k.value] = tuple(rels)
    return out


def _name_tokens(name: str) -> Set[str]:
    """CamelCase/underscore name → lowercase token set:
    ``PagedKvPool`` → {paged, kv, pool}; ``server_controller_pool`` →
    {server, controller, pool}.  Receiver-to-class matching runs on
    token overlap — lexical, honest, and documented."""
    return {t.lower()
            for t in re.findall(r"[A-Z]+[a-z0-9]*|[a-z0-9]+", name)}


class _CustodyRegistry:
    """Every ``_CUSTODY`` declaration across the sweep, merged by
    acquire name — the custody pass's phase-one output."""

    def __init__(self):
        # acquire/field name -> list of decl dicts
        self.by_name: Dict[str, List[dict]] = {}
        # (modname, class_name or None) -> every protocol method name
        # (acquires + releases): their BODIES are the implementation,
        # exempt from the acquire-release rule
        self.protocol: Dict[Tuple[str, Optional[str]], Set[str]] = {}

    def add(self, modname: str, class_name: Optional[str],
            cmap: Dict[str, Tuple[str, ...]]) -> None:
        names = self.protocol.setdefault((modname, class_name), set())
        for name, rels in cmap.items():
            names.add(name)
            names.update(rels)
            self.by_name.setdefault(name, []).append({
                "name": name, "releases": tuple(rels),
                "modname": modname, "class_name": class_name,
                "tokens": (_name_tokens(class_name)
                           if class_name else set()),
            })

    def exempt_fn(self, modname: str, class_name: Optional[str],
                  fn_name: str) -> bool:
        return (fn_name in self.protocol.get((modname, class_name), ())
                or fn_name == "__init__")


class _Held:
    """One lexically-held lock: (receiver base name or None for a
    module-level lock, lock name, canonical graph identity)."""

    __slots__ = ("base", "name", "canonical")

    def __init__(self, base: Optional[str], name: str, canonical: str):
        self.base = base
        self.name = name
        self.canonical = canonical


class ModuleLint:
    """All passes over one module; lock-order edges are merged globally
    by the driver."""

    def __init__(self, path: str, source: str, modname: str):
        self.path = path
        self.source = source
        self.modname = modname
        self.tree = ast.parse(source, filename=path)
        self.directives = _Directives(source, path)
        self.findings: List[Finding] = []
        # canonical lock id -> {canonical lock id -> (path, line)}
        self.lock_edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
        self.import_aliases = self._collect_import_aliases()
        self.class_guards = self._collect_class_guards()
        self.global_guards = self._collect_global_guards()
        self.custody_decls = self._collect_custody()
        self._known_locks = set(self.global_guards.values())
        for g in self.class_guards.values():
            self._known_locks.update(g.values())

    # ---- collection -----------------------------------------------------
    def _collect_import_aliases(self) -> Dict[str, str]:
        """Bound name -> 'resolved.module:orig' for from-imports, so a
        module-level lock imported under an alias keeps one identity."""
        out: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module is not None:
                mod = node.module
                if node.level:
                    parts = self.modname.split(".")
                    base = parts[:max(len(parts) - node.level, 0)]
                    mod = ".".join(base + [node.module])
                for alias in node.names:
                    out[alias.asname or alias.name] = f"{mod}:{alias.name}"
        return out

    def _collect_class_guards(self) -> Dict[str, Dict[str, str]]:
        out: Dict[str, Dict[str, str]] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and stmt.targets[0].id == "_GUARDED_BY"):
                    d = _literal_str_dict(stmt.value)
                    if d is None:
                        self._report("guarded-state", stmt.lineno,
                                     "_GUARDED_BY must be a literal "
                                     "{str: str} dict")
                    else:
                        out[node.name] = d
        return out

    def _collect_custody(self) -> List[Tuple[Optional[str],
                                             Dict[str, Tuple[str, ...]]]]:
        """(class name or None for module scope, map) per _CUSTODY
        declaration; malformed maps report under the custody rule."""
        out: List[Tuple[Optional[str], Dict[str, Tuple[str, ...]]]] = []

        def grab(owner: Optional[str], stmt) -> None:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "_CUSTODY"):
                return
            d = _literal_custody_dict(stmt.value)
            if d is None:
                self._report("custody", stmt.lineno,
                             "_CUSTODY must be a literal {str: tuple-of-"
                             "str} dict")
            else:
                out.append((owner, d))

        for stmt in self.tree.body:
            grab(None, stmt)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                for stmt in node.body:
                    grab(node.name, stmt)
        return out

    def _collect_global_guards(self) -> Dict[str, str]:
        for stmt in self.tree.body:
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "_GUARDED_BY_GLOBALS"):
                d = _literal_str_dict(stmt.value)
                if d is None:
                    self._report("guarded-state", stmt.lineno,
                                 "_GUARDED_BY_GLOBALS must be a literal "
                                 "{str: str} dict")
                    return {}
                return d
        return {}

    # ---- reporting ------------------------------------------------------
    def _report(self, rule: str, line: int, message: str) -> None:
        if self.directives.suppressed(rule, line):
            return
        self.findings.append(Finding(rule, self.path, line, message))

    # ---- lock identity --------------------------------------------------
    def _lockish(self, expr: ast.AST) -> Optional[Tuple[Optional[str], str]]:
        """(base name or None, lock name) when ``expr`` looks like a
        lock; None otherwise.  Calls (``self._dbd.read()``) never are."""
        if isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                            ast.Name):
            name = expr.attr
        else:
            return None
        if not (_LOCKISH_RE.search(name) or name in self._known_locks):
            return None
        if isinstance(expr, ast.Name):
            return (None, name)
        return (expr.value.id, name)

    def _canonical(self, base: Optional[str], name: str,
                   class_name: Optional[str]) -> str:
        if base is None:
            return self.import_aliases.get(name, f"{self.modname}:{name}")
        if base in ("self", "cls") and class_name:
            return f"{class_name}.{name}"
        return f"~{name}"

    # ---- the concurrency walk -------------------------------------------
    def run_concurrency(self) -> None:
        for line, msg in self.directives.bad:
            self.findings.append(
                Finding("bad-suppression", self.path, line, msg))
        self._walk_body(self.tree.body, held=[], class_name=None,
                        fn_node=None, guard_exempt=True)

    def _walk_body(self, body, held, class_name, fn_node, guard_exempt):
        for stmt in body:
            self._walk_stmt(stmt, held, class_name, fn_node, guard_exempt)

    def _walk_stmt(self, node, held, class_name, fn_node, guard_exempt):
        if isinstance(node, ast.ClassDef):
            # class body executes at import (single-threaded): exempt
            self._walk_body(node.body, [], node.name, None, True)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs LATER: locks lexically held around it
            # are not held when it executes — reset the held set
            seeded: List[_Held] = []
            for lock in self.directives.fn_lock_held(node):
                seeded.append(_Held("self", lock,
                                    self._canonical("self", lock,
                                                    class_name)))
            exempt = (class_name is not None and fn_node is None
                      and node.name == "__init__") \
                or self.directives.fn_is_init(node)
            self._walk_body(node.body, seeded, class_name, node, exempt)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in node.items:
                lk = self._lockish(item.context_expr)
                if lk is None:
                    self._visit_exprs(item.context_expr, held, class_name,
                                      fn_node, guard_exempt)
                    continue
                base, name = lk
                canon = self._canonical(base, name, class_name)
                if held and not self.directives.suppressed(
                        "lock-order", node.lineno):
                    outer = held[-1].canonical
                    if outer != canon:
                        self.lock_edges.setdefault(outer, {}) \
                            .setdefault(canon, (self.path, node.lineno))
                held.append(_Held(base, name, canon))
                pushed += 1
            self._walk_body(node.body, held, class_name, fn_node,
                            guard_exempt)
            for _ in range(pushed):
                held.pop()
            return
        # generic statement: visit expressions, recurse into sub-bodies
        for field in ("test", "iter", "value", "targets", "target", "exc",
                      "cause", "msg", "items", "subject"):
            sub = getattr(node, field, None)
            if sub is None:
                continue
            for expr in (sub if isinstance(sub, list) else [sub]):
                if isinstance(expr, ast.AST):
                    self._visit_exprs(expr, held, class_name, fn_node,
                                      guard_exempt)
        for field in ("body", "orelse", "finalbody", "handlers", "cases"):
            sub = getattr(node, field, None)
            if not sub:
                continue
            for child in sub:
                if isinstance(child, (ast.ExceptHandler, ast.match_case)):
                    self._walk_body(child.body, held, class_name, fn_node,
                                    guard_exempt)
                elif isinstance(child, ast.AST):
                    self._walk_stmt(child, held, class_name, fn_node,
                                    guard_exempt)

    def _visit_exprs(self, expr, held, class_name, fn_node, guard_exempt):
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                self._check_attr_access(node, held, class_name, fn_node,
                                        guard_exempt)
                self._check_plane_state_attr(node)
            elif isinstance(node, ast.Name):
                self._check_global_access(node, held, fn_node, guard_exempt)
            elif isinstance(node, ast.Call):
                self._check_blocking(node, held)
                self._check_thread_spawn(node)
                self._check_plane_state_thread(node)
            elif isinstance(node, (ast.Lambda,)):
                pass        # lambdas run later; their bodies are tiny and
                # attribute checks inside would be against a reset held
                # set — handled conservatively by not descending
                # (ast.walk descends anyway; accesses in lambdas are
                # checked against the ENCLOSING held set, a known
                # imprecision kept for simplicity)

    # ---- pass 1: guarded state -----------------------------------------
    def _check_attr_access(self, node: ast.Attribute, held, class_name,
                           fn_node, guard_exempt) -> None:
        if guard_exempt or class_name is None or fn_node is None:
            return
        guards = self.class_guards.get(class_name)
        if not guards or node.attr not in guards:
            return
        if not isinstance(node.value, ast.Name):
            return
        base = node.value.id
        need = guards[node.attr]
        for h in held:
            # a held module-level lock of the declared name also
            # satisfies (instance state guarded by a registry lock —
            # the health-check pattern)
            if h.name == need and (h.base == base or h.base is None):
                return
        if base == "self":
            if need in self.directives.fn_lock_held(fn_node):
                return
        self._report(
            "guarded-state", node.lineno,
            f"{class_name}.{fn_node.name}: access to {base}.{node.attr} "
            f"outside 'with {base}.{need}:' (declared in _GUARDED_BY)")

    def _check_global_access(self, node: ast.Name, held, fn_node,
                             guard_exempt) -> None:
        if guard_exempt or fn_node is None:
            return
        need = self.global_guards.get(node.id)
        if need is None:
            return
        for h in held:
            if h.base is None and h.name == need:
                return
        if need in self.directives.fn_lock_held(fn_node):
            return
        self._report(
            "guarded-state", node.lineno,
            f"{fn_node.name}: access to module global {node.id} outside "
            f"'with {need}:' (declared in _GUARDED_BY_GLOBALS)")

    # ---- pass 3: blocking under lock -----------------------------------
    def _check_blocking(self, node: ast.Call, held) -> None:
        if not held:
            return
        func = node.func
        name = None
        base = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
            base = func.value
        if name is None:
            return
        blocking = False
        if name in _BLOCKING_NAMES:
            blocking = True
        elif name in _SUBPROCESS_NAMES and isinstance(base, ast.Name) \
                and base.id == "subprocess":
            blocking = True
        elif name == "join":
            # distinguish thread.join(timeout?) from str.join(iterable):
            # a str/bytes receiver, or a single non-numeric argument,
            # is string joining
            if isinstance(base, ast.Constant) and isinstance(
                    base.value, (str, bytes)):
                blocking = False
            elif len(node.args) == 0 and not node.keywords:
                blocking = True
            elif (len(node.args) == 1 and not node.keywords
                  and isinstance(node.args[0], ast.Constant)
                  and isinstance(node.args[0].value, (int, float))):
                blocking = True
            elif any(kw.arg == "timeout" for kw in node.keywords):
                blocking = True
        large_copy = name in _LARGE_COPY_NAMES
        if not blocking and not large_copy:
            return
        locks = ", ".join(h.name for h in held)
        if large_copy:
            # a block-sized memcpy/compare serializes every other
            # waiter for the copy's duration (PR 19's demote residue)
            self._report(
                "blocking-under-lock", node.lineno,
                f"large copy '{name}' while holding {locks} — the "
                f"memcpy serializes the lock's other waiters; move it "
                f"outside or suppress with a reason")
            return
        self._report(
            "blocking-under-lock", node.lineno,
            f"call to blocking '{name}' while holding {locks}")

    # ---- pass 4: thread hygiene ----------------------------------------
    def _check_thread_spawn(self, node: ast.Call) -> None:
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if name != "Thread":
            return
        daemon = None
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = kw.value.value
        joined = self._thread_provably_joined(node)
        if daemon is not True and not joined:
            # a thread that is synchronously joined may be non-daemon
            # (CLI worker fan-outs); anything else must not block exit
            self._report(
                "thread-hygiene", node.lineno,
                "threading.Thread spawned without daemon=True and not "
                "provably joined — a non-daemon thread blocks "
                "interpreter exit and races static teardown")
        if joined or self.directives.thread_marker(node.lineno):
            return
        self._report(
            "thread-hygiene", node.lineno,
            "thread has no visible quiesce path: no .join() on its "
            "handle in this module and no '# fablint: "
            "thread-quiesced(<how>)' marker")

    def _thread_provably_joined(self, node: ast.Call) -> bool:
        """Some name transitively holding the spawned thread is
        .join()ed somewhere in this module.  Aliases are chased through
        assignments (``t = Thread(...)``, ``self._r = t``, ``r, self._r
        = self._r, None``) and for-loops over a holding list (``for t
        in threads: t.join()``) — a weak but honest lexical proof."""

        def tname(t):
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Attribute):
                return t.attr
            return None

        assigns = [n for n in ast.walk(self.tree)
                   if isinstance(n, ast.Assign)]
        names: Set[str] = set()
        for a in assigns:
            if any(sub is node for sub in ast.walk(a.value)):
                for t in a.targets:
                    n = tname(t)
                    if n:
                        names.add(n)
        if not names:
            return False
        changed = True
        while changed:
            changed = False
            for a in assigns:
                if (len(a.targets) == 1
                        and isinstance(a.targets[0], ast.Tuple)
                        and isinstance(a.value, ast.Tuple)
                        and len(a.targets[0].elts) == len(a.value.elts)):
                    pairs = list(zip(a.targets[0].elts, a.value.elts))
                else:
                    pairs = [(t, a.value) for t in a.targets]
                for t, v in pairs:
                    vn = tname(v) if isinstance(
                        v, (ast.Name, ast.Attribute)) else None
                    tn = tname(t)
                    if vn in names and tn and tn not in names:
                        names.add(tn)
                        changed = True
            for n in ast.walk(self.tree):
                if isinstance(n, ast.For) and isinstance(n.iter, ast.Name) \
                        and n.iter.id in names \
                        and isinstance(n.target, ast.Name) \
                        and n.target.id not in names:
                    names.add(n.target.id)
                    changed = True
        return any(
            re.search(r"\b%s\s*\.\s*join\s*\(" % re.escape(nm), self.source)
            for nm in names)

    # ---- pass 5: plane-state containment --------------------------------
    def _check_plane_state_attr(self, node: ast.Attribute) -> None:
        if os.path.basename(self.path) == _PLANE_HEALTH_BASENAME:
            return
        if not isinstance(node.ctx, (ast.Store, ast.Del)):
            return
        if not (isinstance(node.value, ast.Name)
                and node.value.id in ("self", "cls")):
            return
        if not _PLANE_STATE_RE.match(node.attr):
            return
        self._report(
            "plane-state", node.lineno,
            f"per-plane health state field '{node.attr}' declared outside "
            f"ici/plane_health.py — register the plane with "
            f"plane_health.register_plane() instead of growing a private "
            f"down/reestablish machine")

    def _check_plane_state_thread(self, node: ast.Call) -> None:
        if os.path.basename(self.path) == _PLANE_HEALTH_BASENAME:
            return
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if name != "Thread":
            return
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            tgt = v.attr if isinstance(v, ast.Attribute) else (
                v.id if isinstance(v, ast.Name) else "")
            if _PLANE_THREAD_RE.search(tgt):
                self._report(
                    "plane-state", node.lineno,
                    f"revival thread (target '{tgt}') spawned outside "
                    f"ici/plane_health.py — the engine owns every plane's "
                    f"revival loop; planes supply only a prober callback")

    # ---- custody passes (ISSUE 20) --------------------------------------
    # Rule "custody": per-function, path-sensitive.  Every acquisition
    # (declared acquire call, +1 on a declared refcount field) must
    # reach a matching release, a custody-moved marker, or an owning
    # return on EVERY exit path, including exception edges: a statement
    # that can raise while custody is held must sit under a try whose
    # broad handler or finally releases.  Rule "refcount-balance":
    # every ±1 on a declared field sits under its lock, and every
    # decrement dominates a zero-check that frees.
    #
    # Honest lexical scope (the runtime ledger covers the rest): only
    # statement-level acquire shapes are tracked — bare call, direct
    # assign (incl. tuple / attribute targets), ``return acquire()``,
    # ``if [not] acquire():`` — an acquire nested in a larger
    # expression (an append argument, a comprehension) is treated as
    # escaping into that expression's owner.

    def run_custody(self, registry: _CustodyRegistry,
                    emit_bad: bool = False) -> None:
        if emit_bad:
            for line, msg in self.directives.bad:
                self.findings.append(
                    Finding("bad-suppression", self.path, line, msg))
        self._registry = registry
        self._fields = {
            name for name, decls in registry.by_name.items()
            if any(d["modname"] == self.modname for d in decls)
            and self._field_decls(name)}
        self._acq_scan(self.tree.body, None)
        self._rc_walk(self.tree.body, None, None, [], [], [])

    def _field_decls(self, field: str) -> List[dict]:
        return [d for d in self._registry.by_name.get(field, ())
                if d["modname"] == self.modname]

    # -- acquisition discovery -------------------------------------------
    def _acq_scan(self, body, class_name) -> None:
        for stmt in body:
            if isinstance(stmt, ast.ClassDef):
                self._acq_scan(stmt.body, stmt.name)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not self._registry.exempt_fn(self.modname, class_name,
                                                stmt.name):
                    self._fn_check_acquires(stmt, class_name)
                self._acq_scan(stmt.body, class_name)
            else:
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        self._acq_scan(sub, class_name)
                for h in getattr(stmt, "handlers", None) or []:
                    self._acq_scan(h.body, class_name)

    def _fn_check_acquires(self, fn, class_name) -> None:
        found: List[Tuple[list, dict]] = []

        def descend(block, chain):
            for i, stmt in enumerate(block):
                here = chain + [(block, i)]
                acq = self._acquire_in_stmt(stmt, class_name)
                if acq is not None:
                    found.append((here, acq))
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue    # nested defs run later: their own scan
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        descend(sub, here)
                for h in getattr(stmt, "handlers", None) or []:
                    descend(h.body, here)

        descend(fn.body, [])
        for path, acq in found:
            self._flow_token(fn, path, acq)

    def _match_acquire_call(self, call, class_name):
        """(releases, root, name) when ``call`` is a declared acquire
        reached through a matching receiver; None otherwise."""
        if not isinstance(call, ast.Call):
            return None
        f = call.func
        recv = root = None
        if isinstance(f, ast.Name):
            name = f.id
        elif isinstance(f, ast.Attribute):
            name = f.attr
            v = f.value
            if isinstance(v, ast.Name):
                recv = root = v.id
            elif isinstance(v, ast.Attribute) and isinstance(v.value,
                                                             ast.Name):
                recv, root = v.attr, v.value.id
            else:
                return None
        else:
            return None
        if recv is not None and recv not in ("self", "cls") \
                and _LOCKISH_RE.search(recv):
            return None           # cv.acquire()/lock.acquire() etc.
        rels: Set[str] = set()
        hit = False
        for d in self._registry.by_name.get(name, ()):
            if d["class_name"] is None:
                if d["modname"] != self.modname:
                    continue
            elif recv in ("self", "cls"):
                if not (d["modname"] == self.modname
                        and d["class_name"] == class_name):
                    continue
            elif recv is None or not (_name_tokens(recv) & d["tokens"]):
                continue
            hit = True
            rels.update(d["releases"])
        if not hit:
            return None
        return rels, root, name

    def _acquire_in_stmt(self, stmt, class_name):
        """Token dict for a statement-level acquisition, or None.
        ``form``: bare | assign | ifnot | ifheld; ``return``-shaped
        acquires are owning-return satisfied and yield no token."""
        def tok(call, m, form, owners):
            rels, root, name = m
            return {"form": form, "line": call.lineno,
                    "stmt_line": stmt.lineno, "name": name,
                    "releases": rels, "root": root,
                    "owners": owners, "field": None, "stmt": stmt}

        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            m = self._match_acquire_call(stmt.value, class_name)
            if m:
                return tok(stmt.value, m, "bare", set())
        elif isinstance(stmt, ast.Assign) \
                and isinstance(stmt.value, ast.Call):
            m = self._match_acquire_call(stmt.value, class_name)
            if m:
                owners: Set[str] = set()
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        owners.add(t.id)
                    elif isinstance(t, ast.Tuple):
                        owners.update(e.id for e in t.elts
                                      if isinstance(e, ast.Name))
                    elif isinstance(t, ast.Attribute):
                        n = t.value
                        while isinstance(n, ast.Attribute):
                            n = n.value
                        if isinstance(n, ast.Name):
                            owners.add(n.id)   # s.sid = pool.get(): s owns
                return tok(stmt.value, m, "assign", owners)
        elif isinstance(stmt, ast.Return) and stmt.value is not None:
            for n in ast.walk(stmt.value):
                if isinstance(n, ast.Call) \
                        and self._match_acquire_call(n, class_name):
                    return None     # returned to the caller: owner moves
        elif isinstance(stmt, ast.If):
            t = stmt.test
            terms = t.values if isinstance(t, ast.BoolOp) else [t]
            for term in terms:
                if isinstance(term, ast.UnaryOp) \
                        and isinstance(term.op, ast.Not) \
                        and isinstance(term.operand, ast.Call):
                    m = self._match_acquire_call(term.operand, class_name)
                    if m:
                        return tok(term.operand, m, "ifnot", set())
                elif isinstance(term, ast.Call):
                    m = self._match_acquire_call(term, class_name)
                    if m:
                        return tok(term, m, "ifheld", set())
        # refcount increment as an acquisition (rule 1 over fields)
        for site in self._refcount_sites(stmt):
            if site["delta"] > 0:
                rels: Set[str] = set()
                for d in self._field_decls(site["field"]):
                    rels.update(d["releases"])
                return {"form": "bare", "line": site["line"],
                        "stmt_line": stmt.lineno, "name": site["field"],
                        "releases": rels, "root": None, "owners": set(),
                        "field": site["field"], "stmt": stmt}
        return None

    # -- the per-token flow ----------------------------------------------
    def _flow_token(self, fn, path, tok) -> None:
        stmt = tok["stmt"]
        if self.directives.moved_marker(tok["line"], tok["stmt_line"]):
            return                  # explicit ownership transfer
        for ln in (tok["line"], tok["stmt_line"]):
            if self.directives.suppressed("custody", ln):
                return
        self._tok_problem = False
        H, R = True, False
        level = len(path) - 1
        if tok["form"] == "ifheld":
            env = self._env_at(path, level, tok)
            out = self._exec_block(stmt.body, 0, {H}, env, tok)
        else:
            out = {"fall": {H}, "break": set(), "continue": set()}
        while not self._tok_problem:
            block, i = path[level]
            env = self._env_at(path, level, tok)
            nxt = self._exec_block(block, i + 1, out["fall"], env, tok)
            out = {"fall": nxt["fall"],
                   "break": out["break"] | nxt["break"],
                   "continue": out["continue"] | nxt["continue"]}
            if level == 0:
                break
            parent_block, pi = path[level - 1]
            parent = parent_block[pi]
            out = self._apply_container(parent, block, out,
                                        self._env_at(path, level - 1, tok),
                                        tok)
            level -= 1
        if not self._tok_problem and H in out["fall"]:
            self._tok_fail(tok, fn.body[-1].lineno,
                           "function can fall off its end with custody "
                           "still held")

    def _tok_fail(self, tok, line: int, what: str) -> None:
        if self._tok_problem:
            return
        self._tok_problem = True
        rels = ", ".join(sorted(tok["releases"])) or "<none declared>"
        self._report(
            "custody", tok["line"],
            f"'{tok['name']}' acquisition {what} (at/after line {line}) "
            f"— release ({rels}), return the owner, or mark "
            f"'# fablint: custody-moved(<to>) <reason>'")

    def _env_at(self, path, level, tok) -> dict:
        env = {"exc_covered": False, "exit_released": False}
        for j in range(level):
            blk, i = path[j]
            stmt = blk[i]
            if not isinstance(stmt, ast.Try):
                continue
            child = path[j + 1][0]
            fin = self._finally_releases(stmt, tok)
            if child is stmt.body:
                if fin:
                    env["exc_covered"] = env["exit_released"] = True
                if self._try_covers(stmt, tok):
                    env["exc_covered"] = True
            elif fin and (child is stmt.orelse
                          or any(child is h.body for h in stmt.handlers)):
                env["exc_covered"] = env["exit_released"] = True
        return env

    def _apply_container(self, parent, child_block, out, env, tok) -> dict:
        if isinstance(parent, (ast.While, ast.For, ast.AsyncFor)) \
                and child_block is parent.body:
            # loop-back and break both eventually exit the loop; held
            # states survive into the code after it
            return {"fall": out["fall"] | out["break"] | out["continue"],
                    "break": set(), "continue": set()}
        if isinstance(parent, ast.Try):
            if self._finally_releases(parent, tok):
                return {"fall": {False} if (out["fall"] or out["break"]
                                            or out["continue"]) else set(),
                        "break": set(), "continue": set()}
            if parent.finalbody and child_block is not parent.finalbody:
                self._exec_block(parent.finalbody, 0, out["fall"], env, tok)
        return out

    def _exec_block(self, stmts, i0, states, env, tok) -> dict:
        cur = set(states)
        brk: Set[bool] = set()
        cont: Set[bool] = set()
        for s in stmts[i0:]:
            if not cur or cur == {False}:
                break
            o = self._exec_stmt(s, cur, env, tok)
            brk |= o["break"]
            cont |= o["continue"]
            cur = o["fall"]
        return {"fall": cur, "break": brk, "continue": cont}

    def _exec_stmt(self, s, states, env, tok) -> dict:
        H = True
        fall = lambda st: {"fall": set(st), "break": set(),
                           "continue": set()}
        if H not in states:
            return fall(states)
        if isinstance(s, ast.Return):
            if not (self._owner_return(s, tok) or env["exit_released"]
                    or (s.value is not None
                        and self._release_call_in(s.value, tok))
                    or self.directives.moved_marker(s.lineno)):
                self._tok_fail(tok, s.lineno, "returns without releasing")
            return fall(())
        if isinstance(s, ast.Raise):
            if not (env["exc_covered"] or env["exit_released"]
                    or self.directives.moved_marker(s.lineno)):
                self._tok_fail(tok, s.lineno, "raises without releasing")
            return fall(())
        if isinstance(s, ast.Break):
            return {"fall": set(), "break": set(states), "continue": set()}
        if isinstance(s, ast.Continue):
            return {"fall": set(), "break": set(),
                    "continue": set(states)}
        if isinstance(s, ast.If):
            self._edge_check(s.test, env, tok)
            b = self._exec_block(s.body, 0, states, env, tok)
            e = (self._exec_block(s.orelse, 0, states, env, tok)
                 if s.orelse else fall(states))
            return {"fall": b["fall"] | e["fall"],
                    "break": b["break"] | e["break"],
                    "continue": b["continue"] | e["continue"]}
        if isinstance(s, (ast.While, ast.For, ast.AsyncFor)):
            self._edge_check(getattr(s, "test", None)
                             or getattr(s, "iter", None), env, tok)
            b = self._exec_block(s.body, 0, states, env, tok)
            exit_states = (set(states) | b["fall"] | b["continue"]
                           | b["break"])
            if s.orelse:
                o = self._exec_block(s.orelse, 0, exit_states, env, tok)
                exit_states = o["fall"] | b["break"]
            return fall(exit_states)
        if isinstance(s, ast.Try):
            if env["exit_released"] or self._finally_releases(s, tok):
                return fall({False})    # every exit passes the release
            cov = env["exc_covered"] or self._try_covers(s, tok)
            env2 = dict(env, exc_covered=cov)
            b = self._exec_block(s.body, 0, states, env2, tok)
            outs = [b]
            for h in s.handlers:
                outs.append(self._exec_block(h.body, 0, states, env, tok))
            if s.orelse:
                outs.append(self._exec_block(s.orelse, 0, b["fall"],
                                             env, tok))
                outs.remove(b)
                outs.insert(0, {"fall": set(), "break": b["break"],
                                "continue": b["continue"]})
            merged = {
                "fall": set().union(*(o["fall"] for o in outs)),
                "break": set().union(*(o["break"] for o in outs)),
                "continue": set().union(*(o["continue"] for o in outs))}
            if s.finalbody:
                f = self._exec_block(s.finalbody, 0,
                                     merged["fall"] or set(states),
                                     env, tok)
                merged["fall"] = f["fall"] if merged["fall"] else set()
            return merged
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                if self._lockish(item.context_expr) is None:
                    self._edge_check(item.context_expr, env, tok)
            return self._exec_block(s.body, 0, states, env, tok)
        if isinstance(s, ast.Match):
            outs = [self._exec_block(c.body, 0, states, env, tok)
                    for c in s.cases]
            outs.append(fall(states))
            return {
                "fall": set().union(*(o["fall"] for o in outs)),
                "break": set().union(*(o["break"] for o in outs)),
                "continue": set().union(*(o["continue"] for o in outs))}
        # simple statement
        if self._release_call_in(s, tok):
            return fall({False})
        self._edge_check(s, env, tok)
        return fall(states)

    def _edge_check(self, node, env, tok) -> None:
        """A call that can raise while custody is held needs enclosing
        try coverage."""
        if node is None or env["exc_covered"] or env["exit_released"] \
                or self._tok_problem:
            return
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if isinstance(f, ast.Name) and f.id in _BENIGN_CALLS:
                continue
            if isinstance(f, ast.Attribute):
                if f.attr in _BENIGN_METHODS:
                    continue
                r = f.value
                while isinstance(r, ast.Attribute):
                    r = r.value
                if isinstance(r, ast.Name) and r.id in _BENIGN_ROOTS:
                    continue
            self._tok_fail(
                tok, n.lineno,
                "can raise before the release — wrap the region in a "
                "try whose broad handler or finally releases")
            return

    def _owner_return(self, s: ast.Return, tok) -> bool:
        if s.value is None or not tok["owners"]:
            return False
        return any(isinstance(n, ast.Name) and n.id in tok["owners"]
                   for n in ast.walk(s.value))

    def _release_call_in(self, node, tok) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Name):
                    nm, root = f.id, None
                elif isinstance(f, ast.Attribute):
                    nm = f.attr
                    r = f.value
                    while isinstance(r, ast.Attribute):
                        r = r.value
                    root = r.id if isinstance(r, ast.Name) else None
                else:
                    continue
                if nm in tok["releases"] and (
                        tok["root"] is None or root is None
                        or root == tok["root"]
                        or (root in ("self", "cls")
                            and tok["root"] in ("self", "cls"))):
                    return True
            if tok["field"] is not None \
                    and self._is_field_decrement(n, tok["field"]):
                return True
        return False

    def _is_field_decrement(self, n, field: str) -> bool:
        def names_field(expr):
            t = expr
            if isinstance(t, ast.Subscript):
                t = t.value
            return isinstance(t, ast.Attribute) and t.attr == field
        if isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Sub) \
                and isinstance(n.value, ast.Constant) \
                and n.value.value == 1:
            return names_field(n.target)
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub) \
                and isinstance(n.right, ast.Constant) \
                and n.right.value == 1 \
                and isinstance(n.left, ast.Call) \
                and isinstance(n.left.func, ast.Attribute) \
                and n.left.func.attr == "get":
            return names_field(n.left.func.value)
        return False

    def _try_covers(self, t: ast.Try, tok) -> bool:
        """A broad handler that releases covers exception edges."""
        for h in t.handlers:
            broad = h.type is None
            if not broad:
                types = (h.type.elts if isinstance(h.type, ast.Tuple)
                         else [h.type])
                broad = any(
                    (isinstance(x, ast.Name) and x.id in _BROAD_EXC_NAMES)
                    or (isinstance(x, ast.Attribute)
                        and x.attr in _BROAD_EXC_NAMES)
                    for x in types)
            if broad and any(self._release_call_in(s, tok)
                             for s in h.body):
                return True
        return False

    def _finally_releases(self, t: ast.Try, tok) -> bool:
        return any(self._release_call_in(s, tok) for s in t.finalbody)

    # -- refcount-balance -------------------------------------------------
    def _refcount_sites(self, stmt) -> List[dict]:
        if not getattr(self, "_fields", None):
            return []

        def field_of(expr):
            t = expr
            if isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute) and t.attr in self._fields:
                return t.attr
            return None

        out = []
        if isinstance(stmt, ast.AugAssign) \
                and isinstance(stmt.op, (ast.Add, ast.Sub)) \
                and isinstance(stmt.value, ast.Constant) \
                and stmt.value.value == 1:
            f = field_of(stmt.target)
            if f:
                out.append({"field": f, "line": stmt.lineno, "var": None,
                            "form": "aug",
                            "delta": 1 if isinstance(stmt.op, ast.Add)
                            else -1})
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.value, ast.BinOp) \
                and isinstance(stmt.value.op, (ast.Add, ast.Sub)) \
                and isinstance(stmt.value.right, ast.Constant) \
                and stmt.value.right.value == 1 \
                and isinstance(stmt.value.left, ast.Call) \
                and isinstance(stmt.value.left.func, ast.Attribute) \
                and stmt.value.left.func.attr == "get":
            f = field_of(stmt.value.left.func.value)
            if f:
                t = stmt.targets[0]
                out.append({
                    "field": f, "line": stmt.lineno,
                    "var": t.id if isinstance(t, ast.Name) else None,
                    "form": "get",
                    "delta": 1 if isinstance(stmt.value.op, ast.Add)
                    else -1})
        return out

    def _rc_walk(self, body, class_name, fn_node, held, chain,
                 anc_ifs) -> None:
        """Refcount-balance walk: lock context + sibling chain for the
        zero-check dominance scan."""
        for i, stmt in enumerate(body):
            here = chain + [(body, i)]
            if isinstance(stmt, ast.ClassDef):
                self._rc_walk(stmt.body, stmt.name, None, [], [], [])
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                seeded = list(self.directives.fn_lock_held(stmt))
                self._rc_walk(stmt.body, class_name, stmt, seeded, [], [])
                continue
            if fn_node is not None:
                for site in self._refcount_sites(stmt):
                    self._check_refcount_site(site, class_name, fn_node,
                                              held, here, anc_ifs)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in stmt.items:
                    lk = self._lockish(item.context_expr)
                    if lk is not None:
                        held.append(lk[1])
                        pushed += 1
                self._rc_walk(stmt.body, class_name, fn_node, held, here,
                              anc_ifs)
                for _ in range(pushed):
                    held.pop()
                continue
            if isinstance(stmt, ast.If):
                self._rc_walk(stmt.body, class_name, fn_node, held, here,
                              anc_ifs + [stmt])
                self._rc_walk(stmt.orelse, class_name, fn_node, held,
                              here, anc_ifs)
                continue
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    self._rc_walk(sub, class_name, fn_node, held, here,
                                  anc_ifs)
            for h in getattr(stmt, "handlers", None) or []:
                self._rc_walk(h.body, class_name, fn_node, held, here,
                              anc_ifs)

    def _check_refcount_site(self, site, class_name, fn_node, held,
                             chain, anc_ifs) -> None:
        field = site["field"]
        decls = self._field_decls(field)
        # required lock: the field's _GUARDED_BY entry in its declaring
        # class, else any held lock
        need = None
        for d in decls:
            if d["class_name"] and d["class_name"] in self.class_guards:
                need = self.class_guards[d["class_name"]].get(field, need)
        marked = self.directives.fn_lock_held(fn_node)
        if need is not None:
            guarded = need in held or need in marked
        else:
            guarded = bool(held) or bool(marked)
        if not guarded:
            self._report(
                "refcount-balance", site["line"],
                f"±1 on declared refcount field '{field}' outside "
                + (f"'with {need}:'" if need else "any held lock")
                + " — refcount mutations must be serialized")
        if site["delta"] > 0:
            return
        if not self._decrement_zero_checked(site, chain, anc_ifs):
            self._report(
                "refcount-balance", site["line"],
                f"decrement of refcount field '{field}' has no "
                f"dominating zero-check that frees — a count that "
                f"reaches zero silently strands the resource (guard "
                f"with '> 1', or follow with 'if r <= 0: free()')")

    def _decrement_zero_checked(self, site, chain, anc_ifs) -> bool:
        field, var = site["field"], site["var"]
        # shape 1: decrement under an `if F.get(k, d) > 1:` guard —
        # provably never reaches zero
        for iff in anc_ifs:
            for n in ast.walk(iff.test):
                if isinstance(n, ast.Compare) and len(n.ops) == 1 \
                        and isinstance(n.ops[0], (ast.Gt, ast.GtE)) \
                        and isinstance(n.comparators[0], ast.Constant) \
                        and n.comparators[0].value >= 1 \
                        and self._mentions_field(n.left, field):
                    return True
        # shape 2: a later sibling (at any enclosing level) checks the
        # result and frees
        for block, idx in chain:
            for stmt in block[idx + 1:]:
                for n in ast.walk(stmt):
                    if not isinstance(n, ast.If):
                        continue
                    if self._zero_test(n.test, field, var) \
                            and self._frees(n.body):
                        return True
        return False

    def _mentions_field(self, expr, field: str) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and n.attr == field:
                return True
        return False

    def _zero_test(self, test, field: str, var) -> bool:
        for n in ast.walk(test):
            if var is not None and isinstance(n, ast.Compare) \
                    and isinstance(n.left, ast.Name) and n.left.id == var \
                    and len(n.ops) == 1 \
                    and isinstance(n.ops[0], (ast.LtE, ast.Lt, ast.Eq)):
                return True
            if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not) \
                    and self._mentions_field(n.operand, field):
                return True
            if isinstance(n, ast.Compare) and len(n.ops) == 1 \
                    and isinstance(n.ops[0], (ast.LtE, ast.Lt, ast.Eq)) \
                    and self._mentions_field(n.left, field):
                return True
        return False

    def _frees(self, body) -> bool:
        for stmt in body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Delete):
                    return True
                if isinstance(n, ast.Call):
                    f = n.func
                    nm = f.attr if isinstance(f, ast.Attribute) else (
                        f.id if isinstance(f, ast.Name) else "")
                    if _FREEISH_RE.search(nm):
                        return True
        return False

    # ---- dead-code passes ----------------------------------------------
    def run_deadcode(self) -> None:
        self._dead_imports()
        self._unreachable()
        self._dead_globals()

    def _used_names(self) -> Set[str]:
        used: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                # x.y.z — count the root name (handled by Name Load) and
                # string re-exports via __all__ below
                pass
        for stmt in self.tree.body:
            if (isinstance(stmt, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "__all__"
                            for t in stmt.targets)
                    and isinstance(stmt.value, (ast.List, ast.Tuple))):
                for elt in stmt.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str):
                        used.add(elt.value)
        return used

    def _dead_imports(self) -> None:
        if os.path.basename(self.path) == "__init__.py":
            return              # re-export modules: imports ARE the API
        used = self._used_names()
        for node in ast.walk(self.tree):
            aliases = []
            if isinstance(node, ast.Import):
                aliases = node.names
            elif isinstance(node, ast.ImportFrom):
                aliases = node.names
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "__future__":
                continue
            for alias in aliases:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name.split(".")[0]
                if bound in used:
                    continue
                if node.lineno in self.directives.noqa:
                    continue
                self._report("dead-import", node.lineno,
                             f"'{bound}' imported but never used")

    def _unreachable(self) -> None:
        for node in ast.walk(self.tree):
            for field in ("body", "orelse", "finalbody"):
                body = getattr(node, field, None)
                if not isinstance(body, list):
                    continue
                terminated = False
                for stmt in body:
                    if terminated:
                        self._report("unreachable", stmt.lineno,
                                     "statement is unreachable (follows "
                                     "return/raise/break/continue)")
                        break
                    if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                         ast.Continue)):
                        terminated = True
            if isinstance(node, (ast.If, ast.While)) and isinstance(
                    node.test, ast.Constant) and node.test.value is False:
                self._report("unreachable", node.lineno,
                             "branch condition is literally False")

    def _dead_globals(self) -> None:
        used = self._used_names()
        stores: Dict[str, int] = {}
        for stmt in self.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    stores.setdefault(t.id, stmt.lineno)
        for name, line in sorted(stores.items(), key=lambda kv: kv[1]):
            if not name.startswith("_") or name.startswith("__"):
                continue        # public names may be imported elsewhere
            if name in used or name in ("_GUARDED_BY_GLOBALS",
                                        "_CUSTODY"):
                continue
            if line in self.directives.noqa:
                continue
            self._report("dead-global", line,
                         f"module-level private name '{name}' is written "
                         f"but never read in this module")


# ---- driver -------------------------------------------------------------

def _iter_py_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for f in sorted(files):
                    if f.endswith(".py") and not f.endswith("_pb2.py"):
                        out.append(os.path.join(root, f))   # _pb2: generated
        elif p.endswith(".py"):
            out.append(p)
    return out


def _modname_for(path: str) -> str:
    """Dotted module name: walk up while __init__.py exists."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    name = ".".join(reversed(parts))
    return name[:-len(".__init__")] if name.endswith(".__init__") else name


def _find_cycles(graph: Dict[str, Dict[str, Tuple[str, int]]]
                 ) -> List[List[str]]:
    """Cycles in the acquisition digraph (one representative per SCC
    with a cycle), via iterative Tarjan."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v0: str) -> None:
        work = [(v0, iter(graph.get(v0, ())))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on_stack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(graph.get(w, ()))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1 or v in graph.get(v, ()):
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def run(paths: List[str], rules: Tuple[str, ...]) -> List[Finding]:
    findings: List[Finding] = []
    edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
    want_conc = any(r in rules for r in CONCURRENCY_RULES)
    want_dead = any(r in rules for r in DEADCODE_RULES)
    want_cust = any(r in rules for r in CUSTODY_RULES)
    # phase 1: parse everything — custody declarations are cross-file
    # (``pool.pin`` in migration.py resolves against kv_pool's map)
    lints: List[ModuleLint] = []
    registry = _CustodyRegistry()
    for path in _iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            lint = ModuleLint(path, source, _modname_for(path))
        except SyntaxError as e:
            findings.append(Finding("parse-error", path, e.lineno or 0,
                                    str(e)))
            continue
        lints.append(lint)
        if want_cust:
            for class_name, cmap in lint.custody_decls:
                registry.add(lint.modname, class_name, cmap)
    # phase 2: analyze
    for lint in lints:
        if want_conc:
            lint.run_concurrency()
        if want_cust:
            lint.run_custody(registry, emit_bad=not want_conc)
        if want_dead:
            lint.run_deadcode()
        findings.extend(f for f in lint.findings if f.rule in rules
                        or f.rule == "parse-error")
        for src, dsts in lint.lock_edges.items():
            for dst, loc in dsts.items():
                edges.setdefault(src, {}).setdefault(dst, loc)
    if "lock-order" in rules:
        for comp in _find_cycles(edges):
            locs = []
            for a in comp:
                for b, (p, ln) in edges.get(a, {}).items():
                    if b in comp:
                        locs.append(f"{a} -> {b} at {p}:{ln}")
            first = edges[comp[0]]
            path0, line0 = next(iter(first.values()))
            findings.append(Finding(
                "lock-order", path0, line0,
                "lock acquisition cycle: " + "; ".join(sorted(locs))))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lock_order_edges(paths: List[str]
                     ) -> Dict[str, Dict[str, Tuple[str, int]]]:
    """The extracted acquisition graph (docs/CONCURRENCY.md generator)."""
    edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
    for path in _iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            lint = ModuleLint(path, source, _modname_for(path))
        except SyntaxError:
            continue
        lint.run_concurrency()
        for src, dsts in lint.lock_edges.items():
            for dst, loc in dsts.items():
                edges.setdefault(src, {}).setdefault(dst, loc)
    return edges


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    only: Optional[Tuple[str, ...]] = None
    out: List[str] = []
    i = 0
    while i < len(argv):
        if argv[i] == "--rules":
            if i + 1 >= len(argv):
                print("fablint: --rules needs a comma-separated list",
                      file=sys.stderr)
                return 2
            only = tuple(r.strip() for r in argv[i + 1].split(",")
                         if r.strip())
            i += 2
        elif argv[i].startswith("--rules="):
            only = tuple(r.strip()
                         for r in argv[i].split("=", 1)[1].split(",")
                         if r.strip())
            i += 1
        else:
            out.append(argv[i])
            i += 1
    argv = out
    cmd = "check"
    if argv and argv[0] in ("check", "deadcode", "custody", "all"):
        cmd = argv.pop(0)
    paths = argv or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    rules = {"check": CONCURRENCY_RULES,
             "deadcode": DEADCODE_RULES,
             "custody": CUSTODY_RULES + ("bad-suppression",),
             "all": ALL_RULES}[cmd]
    if only is not None:
        bad = [r for r in only if r not in ALL_RULES]
        if bad:
            print(f"fablint: unknown rule(s) {', '.join(bad)} — "
                  f"choose from {', '.join(ALL_RULES)}",
                  file=sys.stderr)
            return 2
        rules = tuple(r for r in rules if r in only) or only
    findings = run(paths, rules)
    if as_json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        print(f"fablint: {len(findings)} finding(s) "
              f"[{cmd}] over {len(_iter_py_files(paths))} file(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
