"""rpc_view: render another server's builtin pages from the CLI.

Reference: tools/rpc_view — a proxy that fetches and displays a remote
server's admin pages.  Works against any transport the target listens on
(tcp via HTTP; mem/ici via the HTTP protocol over that transport), and
against any NAMING url (``pod://``, ``mesh://``, ``list://…``) or
comma-separated endpoint list: every resolved member's page is rendered
in its own section.  Empty resolution is a hard error — a typo'd pod
name must not silently show nothing.

    python -m brpc_tpu.tools.rpc_view --server 127.0.0.1:8000 --page status
    python -m brpc_tpu.tools.rpc_view --server pod://default --page rpcz \
        --query trace_id=abcd
"""
from __future__ import annotations

import argparse
import sys
import urllib.request
from typing import List, Tuple


def resolve_servers(server: str) -> List[str]:
    """One target per resolved member — the shared
    policy.naming.resolve_servers (naming url / comma list / single
    endpoint); ValueError propagates on empty resolution."""
    import brpc_tpu.policy  # noqa: F401
    from brpc_tpu.policy.naming import resolve_servers as _resolve
    return _resolve(server)


def fetch_page(server: str, page: str, query: str = "") -> str:
    if server.startswith(("mem://", "ici://")):
        # in-process targets: speak the HTTP protocol over the fabric socket
        import brpc_tpu.policy  # noqa: F401
        from brpc_tpu.butil.endpoint import parse_endpoint
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.rpc.socket_map import SocketMap
        from brpc_tpu.rpc.input_messenger import InputMessenger
        from brpc_tpu.policy import http as http_proto
        import threading

        got = {}
        evt = threading.Event()

        def process_response(msg, socket):
            got["msg"] = msg
            evt.set()

        proto = http_proto.Protocol(
            name="http_view", parse=http_proto.parse,
            process_response=process_response)
        messenger = InputMessenger(protocols=[proto])
        sock = SocketMap.instance().get_short_socket(
            parse_endpoint(server), messenger)
        req = IOBuf()
        req.append(f"GET /{page}{'?' + query if query else ''} HTTP/1.1\r\n"
                   f"Host: {server}\r\n\r\n")
        sock.write(req)
        if not evt.wait(5):
            raise TimeoutError("no response")
        msg = got["msg"]
        from brpc_tpu.rpc import errors as _e
        sock.set_failed(_e.ECLOSE, "view done")
        return msg.body.decode("utf-8", "replace")
    url = f"http://{server}/{page}{'?' + query if query else ''}"
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read().decode("utf-8", "replace")


def fetch_pages(server: str, page: str,
                query: str = "") -> List[Tuple[str, str]]:
    """(target, body) for every member ``server`` resolves to, fetched
    CONCURRENTLY — pod membership keeps crashed members' records up by
    design, so per-member timeouts must overlap or each dead member
    stalls the CLI for a full timeout in turn.  A member that fails to
    answer contributes its error text as the body — one dead member
    must not hide the rest of the pod."""
    import threading
    targets = resolve_servers(server)
    bodies: List[str] = [""] * len(targets)

    def fetch(i, target):
        try:
            bodies[i] = fetch_page(target, page, query)
        except Exception as e:
            bodies[i] = f"<error: {type(e).__name__}: {e}>\n"

    threads = [threading.Thread(target=fetch, args=(i, t), daemon=True)
               for i, t in enumerate(targets)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    return list(zip(targets, bodies))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--server", required=True,
                    help="endpoint, comma-separated list, or naming url "
                         "(pod://, mesh://, list://, file://, …)")
    ap.add_argument("--page", default="status")
    ap.add_argument("--query", default="")
    args = ap.parse_args(argv)
    try:
        pages = fetch_pages(args.server, args.page, args.query)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    if len(pages) == 1:
        print(pages[0][1])
        return 0
    for target, body in pages:
        print(f"=== {target} ===")
        print(body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
