"""rpc_view: render another server's builtin pages from the CLI.

Reference: tools/rpc_view — a proxy that fetches and displays a remote
server's admin pages.  Works against any transport the target listens on
(tcp via HTTP; mem/ici via the HTTP protocol over that transport).

    python -m brpc_tpu.tools.rpc_view --server 127.0.0.1:8000 --page status
"""
from __future__ import annotations

import argparse
import sys
import urllib.request


def fetch_page(server: str, page: str, query: str = "") -> str:
    if server.startswith(("mem://", "ici://")):
        # in-process targets: speak the HTTP protocol over the fabric socket
        import brpc_tpu.policy  # noqa: F401
        from brpc_tpu.butil.endpoint import parse_endpoint
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.rpc.socket_map import SocketMap
        from brpc_tpu.rpc.input_messenger import InputMessenger
        from brpc_tpu.policy import http as http_proto
        import threading

        got = {}
        evt = threading.Event()

        def process_response(msg, socket):
            got["msg"] = msg
            evt.set()

        proto = http_proto.Protocol(
            name="http_view", parse=http_proto.parse,
            process_response=process_response)
        messenger = InputMessenger(protocols=[proto])
        sock = SocketMap.instance().get_short_socket(
            parse_endpoint(server), messenger)
        req = IOBuf()
        req.append(f"GET /{page}{'?' + query if query else ''} HTTP/1.1\r\n"
                   f"Host: {server}\r\n\r\n")
        sock.write(req)
        if not evt.wait(5):
            raise TimeoutError("no response")
        msg = got["msg"]
        from brpc_tpu.rpc import errors as _e
        sock.set_failed(_e.ECLOSE, "view done")
        return msg.body.decode("utf-8", "replace")
    url = f"http://{server}/{page}{'?' + query if query else ''}"
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read().decode("utf-8", "replace")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--server", required=True)
    ap.add_argument("--page", default="status")
    ap.add_argument("--query", default="")
    args = ap.parse_args(argv)
    print(fetch_page(args.server, args.page, args.query))
    return 0


if __name__ == "__main__":
    sys.exit(main())
