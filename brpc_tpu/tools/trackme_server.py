"""trackme_server — receives version pings and serves bulletins.

Reference: tools/trackme_server/ (a server counting per-version pings and
answering with warnings for known-bad versions).  Run standalone:

    python -m brpc_tpu.tools.trackme_server --port 8877

or embed TrackMeService in any Server.  Bad-version ranges can be added
with add_bulletin(); ping counts are exposed via bvar
(trackme_ping_count) so /vars shows adoption."""
from __future__ import annotations

import threading
from typing import Dict, List, Tuple

from .. import bvar
from ..butil import logging as log
from ..proto.trackme_pb2 import (TrackMeRequest, TrackMeResponse,
                                 TRACKME_OK, TRACKME_WARNING)
from ..rpc import Service, method

_g_pings = bvar.Adder("trackme_ping_count")


class TrackMeService(Service):
    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self._version_counts: Dict[int, int] = {}
        # (min_version, max_version, severity, text)
        self._bulletins: List[Tuple[int, int, int, str]] = []

    def add_bulletin(self, min_version: int, max_version: int,
                     severity: int, text: str) -> None:
        with self._lock:
            self._bulletins.append((min_version, max_version, severity,
                                    text))

    def version_counts(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._version_counts)

    @method(TrackMeRequest, TrackMeResponse)
    def TrackMe(self, cntl, request, response, done):
        _g_pings << 1
        with self._lock:
            self._version_counts[request.rpc_version] = \
                self._version_counts.get(request.rpc_version, 0) + 1
            hits = [b for b in self._bulletins
                    if b[0] <= request.rpc_version <= b[1]]
        response.severity = TRACKME_OK
        for _, _, severity, text in hits:
            if severity >= response.severity:
                response.severity = severity
                response.error_text = text
        log.info("trackme ping: version=%d from %s", request.rpc_version,
                 request.server_addr or cntl.remote_side)
        done()


def main() -> None:
    import argparse
    from ..rpc import Server
    parser = argparse.ArgumentParser(description="trackme bulletin server")
    parser.add_argument("--port", type=int, default=8877)
    parser.add_argument("--warn-below", type=int, default=0,
                        help="warn versions below this value")
    args = parser.parse_args()
    svc = TrackMeService()
    if args.warn_below:
        svc.add_bulletin(0, args.warn_below - 1, TRACKME_WARNING,
                         f"please upgrade to >= {args.warn_below}")
    server = Server()
    server.add_service(svc)
    if server.start(f"0.0.0.0:{args.port}") != 0:
        raise SystemExit("failed to start")
    log.info("trackme_server listening on %d", args.port)
    server.join()


if __name__ == "__main__":
    main()
