"""rpc_press: protocol-generic load generator.

Reference: tools/rpc_press — fires requests at a target qps (or max), from a
JSON request body, reporting qps/latency through bvar.  Usage:

    python -m brpc_tpu.tools.rpc_press --server mem://echo \
        --method EchoService.Echo --request '{"message":"x"}' \
        --qps 1000 --duration 5 [--proto tests/echo_pb2:EchoRequest,EchoResponse]

``--server`` also accepts a comma-separated endpoint list
(``mem://a,mem://b`` / ``ici://0,ici://2``) or a naming url
(``mesh://``, ``pod://name``, ``list://...``): one channel per resolved
endpoint, workers spread round-robin, and the summary — including the
graceful-SIGINT one — reports per-endpoint sent/error/qps counts, so a
pod/overload bench can drive N servers from one process and see which
member misbehaved.

Mixed-class load (the admission-control adversary): ``--priority`` takes
a single band (``--priority 2``) or a ``band:weight,...`` mix
(``--priority 0:1,3:3`` = one critical per three sheddable); ``--tenant``
takes a name or a ``tenant:weight,...`` mix (``--tenant a:2,b:1``).
Each request draws its (priority, tenant) from the weighted mixes, and
the summary adds per-class sent/shed(ELIMIT)/error/latency so an
overloaded server's shed fairness is visible from the load generator.
"""
from __future__ import annotations

import argparse
import importlib
import json
import signal
import sys
import threading
import time
from typing import List, Optional


def _load_classes(spec: str):
    mod_name, _, names = spec.partition(":")
    req_name, _, resp_name = names.partition(",")
    mod = importlib.import_module(mod_name.replace("/", ".").rstrip(".py"))
    return getattr(mod, req_name), getattr(mod, resp_name)


def parse_weighted_mix(spec: str, *, int_keys: bool = False) -> list:
    """``"a:2,b:1"`` → [("a", 2), ("b", 1)]; a bare ``"a"`` is weight 1.
    With ``int_keys`` the keys are parsed as ints (priority bands).
    Returns an expanded selection wheel: each class repeated weight
    times, so ``wheel[i % len(wheel)]`` draws the mix deterministically."""
    wheel = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        try:
            weight = int(w) if w else 1
        except ValueError:
            raise SystemExit(f"rpc_press: bad weight in {part!r}")
        if weight < 1:
            raise SystemExit(f"rpc_press: weight must be >= 1 in {part!r}")
        key = name.strip()
        if int_keys:
            try:
                key = int(key)
            except ValueError:
                raise SystemExit(f"rpc_press: bad priority in {part!r}")
        wheel.extend([key] * weight)
    return wheel


def resolve_targets(server: str) -> List[str]:
    """One endpoint url per target channel — the shared
    policy.naming.resolve_servers (naming url / comma list / single
    endpoint), with empty resolution as the CLI's hard exit."""
    from ..policy.naming import resolve_servers
    try:
        return resolve_servers(server)
    except ValueError as e:
        raise SystemExit(f"rpc_press: {e}")


BULK_PLANES = ("auto", "shm", "uds", "inline")


def apply_bulk_plane(mode: str) -> None:
    """Pin the fabric bulk tier for this process: "auto" keeps the route
    table's preference (shm > uds/tcp > inline), "shm" force-enables the
    shm flag (it already outranks the rest; whether a ring actually
    bound is visible in the summary's per-route counters — the /dev/shm
    capability probe cannot be forced), "uds" disables the shm ring so
    payloads take the socket conn, "inline" disables both descriptor
    planes so everything rides the control channel."""
    if mode not in BULK_PLANES:
        raise SystemExit(f"rpc_press: unknown --bulk-plane {mode!r} "
                         f"(choose from {', '.join(BULK_PLANES)})")
    if mode == "auto":
        return
    import brpc_tpu.ici.fabric  # noqa: F401 — defines the ici_fabric_* flags
    from brpc_tpu.butil import flags as _fl
    if mode == "shm":
        _fl.set_flag("ici_fabric_shm", True)
    elif mode == "uds":
        _fl.set_flag("ici_fabric_shm", False)
    elif mode == "inline":
        _fl.set_flag("ici_fabric_shm", False)
        _fl.set_flag("ici_fabric_bulk", False)


USERCODE_POOLS = ("auto", "pthread", "subinterp", "off")


def apply_usercode_pool(mode: str) -> None:
    """Pin the usercode-pool backend for servers hosted IN THIS process
    (mem:// targets, self-hosted ici:// members): "auto" keeps each
    server's configured resolution, "pthread"/"subinterp" override the
    default backend before those servers start, "off" just records the
    pin (a load generator cannot un-pool a remote server).  The summary
    reports the probed isolation capability either way, plus per-server
    pool stats for every in-process server that carries a pool."""
    if mode not in USERCODE_POOLS:
        raise SystemExit(f"rpc_press: unknown --usercode-pool {mode!r} "
                         f"(choose from {', '.join(USERCODE_POOLS)})")
    if mode in ("pthread", "subinterp"):
        from brpc_tpu.rpc import usercode_pool as _up
        try:
            _up.set_default_kind(mode)
        except ValueError as e:
            raise SystemExit(f"rpc_press: {e}")


def collect_usercode_pool_stats() -> dict:
    """The summary's pool block: the process isolation capability
    (probe record incl. the no-scaling reason) + describe() of every
    in-process server's pool (loopback registry + native ici
    bindings)."""
    from brpc_tpu.rpc.usercode_pool import probe_isolation
    out: dict = {"isolation": probe_isolation()._asdict(), "servers": {}}
    seen = set()
    try:
        from brpc_tpu.rpc import loopback
        with loopback._servers_lock:
            servers = list(loopback._servers.items())
        for name, srv in servers:
            pool = getattr(srv, "usercode_pool", None)
            if pool is not None and hasattr(pool, "describe") \
                    and id(srv) not in seen:
                seen.add(id(srv))
                out["servers"][f"mem://{name}"] = pool.describe()
    except Exception:
        pass
    try:
        from brpc_tpu.ici import native_plane
        with native_plane._server_bindings_lock:
            bindings = list(native_plane._server_bindings.items())
        for dev, b in bindings:
            pool = getattr(b._server, "usercode_pool", None)
            if pool is not None and hasattr(pool, "describe") \
                    and id(b._server) not in seen:
                seen.add(id(b._server))
                out["servers"][f"ici://{dev}"] = pool.describe()
    except Exception:
        pass
    return out


def run_press_fanout(server: str, method: str, n: int,
                     duration: float = 5.0, concurrency: int = 2,
                     shard_bytes: int = 512, out=sys.stderr) -> dict:
    """``--fanout N``: drive ONE ParallelChannel over the first N
    resolved members (pod://name, mesh://, a comma list) with a
    sharded operand per call — the compiled collective route where the
    members registered a device handler, the per-member RPC loop where
    they did not (or the route degraded).  The summary reports fan-out
    p50/p99 plus PER-ROUTE call counts and the route-table event
    counters, so a degraded pod is visible from the load generator."""
    import numpy as np

    import brpc_tpu.policy  # noqa: F401
    from brpc_tpu import rpc, bvar, channels
    targets = resolve_targets(server)
    if len(targets) < n:
        raise SystemExit(f"rpc_press: --fanout {n} needs {n} members, "
                         f"resolved {len(targets)}")
    targets = targets[:n]
    pc = channels.ParallelChannel()
    mapper = channels.ShardingCallMapper()
    merger = channels.CollectiveMerger(merge=channels.MERGE_GATHER,
                                       dtype="uint8",
                                       shard_shape=(shard_bytes,))
    for t in targets:
        ch = rpc.Channel()
        ch.init(t, options=rpc.ChannelOptions(timeout_ms=10000))
        pc.add_channel(ch, mapper=mapper, merger=merger)
    op = np.arange(n * shard_bytes, dtype=np.uint8).reshape(n,
                                                            shard_bytes)
    recorder = bvar.LatencyRecorder()
    sent = [0]
    errors_count = [0]
    routes: dict = {}
    lock = threading.Lock()
    deadline = time.monotonic() + duration
    stop_evt = threading.Event()
    prev_sigint = None
    try:
        prev_sigint = signal.signal(signal.SIGINT,
                                    lambda *_: stop_evt.set())
    except ValueError:
        pass

    def worker():
        while not stop_evt.is_set() and time.monotonic() < deadline:
            cntl = rpc.Controller()
            cntl.fanout_operand = op
            t0 = time.perf_counter_ns()
            pc.call_method(method, cntl, b"", None)
            lat_us = (time.perf_counter_ns() - t0) // 1000
            route = cntl.fanout_route or "none"
            with lock:
                sent[0] += 1
                routes[route] = routes.get(route, 0) + 1
                if cntl.failed():
                    errors_count[0] += 1
                else:
                    recorder << lat_us

    threads = [threading.Thread(target=worker)
               for _ in range(max(concurrency, 1))]
    t_start = time.monotonic()
    for t in threads: t.start()
    for t in threads: t.join()
    elapsed = time.monotonic() - t_start
    if prev_sigint is not None:
        try:
            signal.signal(signal.SIGINT, prev_sigint)
        except ValueError:
            pass
    from brpc_tpu.bvar import SamplerCollector
    SamplerCollector.instance().sample_once()
    result = {
        "fanout": n,
        "members": targets,
        "sent": sent[0],
        "errors": errors_count[0],
        "qps": round(sent[0] / elapsed, 1) if elapsed else 0.0,
        "fanout_p50_us": recorder.latency_percentile(0.5),
        "fanout_p99_us": recorder.latency_percentile(0.99),
        "avg_latency_us": round(recorder.latency(), 1),
        "per_route": routes,
        "interrupted": stop_evt.is_set(),
    }
    try:
        from brpc_tpu.ici.route import collective_stats
        cs = collective_stats()
        if cs:
            result["route_counters"] = cs
    except Exception:
        pass
    print(json.dumps(result), file=out)
    return result


def collect_serving_stats() -> dict:
    """The serving summary block: describe_serving() of every serving
    service hosted IN THIS process (loopback registry + native ici
    bindings) — pool occupancy, step rate, batch occupancy, router
    weights.  Remote-only runs report an empty dict (the /status page
    on the server carries the same block)."""
    out: dict = {}
    seen = set()

    def scan(server, label):
        if id(server) in seen:
            return
        seen.add(id(server))
        for name, svc in server.services().items():
            fn = getattr(svc, "describe_serving", None)
            if callable(fn):
                try:
                    out[f"{label}/{name}"] = fn()
                except Exception:
                    pass
    try:
        from brpc_tpu.rpc import loopback
        with loopback._servers_lock:
            servers = list(loopback._servers.items())
        for name, srv in servers:
            scan(srv, f"mem://{name}")
    except Exception:
        pass
    try:
        from brpc_tpu.ici import native_plane
        with native_plane._server_bindings_lock:
            bindings = list(native_plane._server_bindings.items())
        for dev, b in bindings:
            scan(b._server, f"ici://{dev}")
    except Exception:
        pass
    return out


def run_press_serving(server: str, duration: float = 5.0,
                      arrival_rps: float = 20.0, batch_ratio: int = 3,
                      seq_range: str = "32-96", steps_range: str = "8-64",
                      max_sessions_inflight: int = 64, verify: bool = False,
                      out=sys.stderr) -> dict:
    """``--serving``: OPEN-LOOP session generator against a serving
    router (``Router.Generate``).  Sessions arrive at a fixed rate
    regardless of completions (the arrival clock never waits for the
    server — the load shape a shedding admission layer must absorb),
    drawn from a mixed population: 1 INTERACTIVE session (priority 0,
    tenant "inter", short decode) per ``batch_ratio`` BATCH sessions
    (priority 3, tenant "bulk", long decode).  The summary reports
    per-tenant session counts, shed/failure split, per-session
    tokens/s p50/p99, end-to-end latency, and the serving /status
    block (pool occupancy, step rate, batch occupancy) for every
    in-process serving server, plus each in-process pool's
    ``kv_prefix`` CoW block (shared_blocks / prefix_hits /
    sharing_ratio, ISSUE 16) and ``kv_tiers`` tiered-memory block
    (spilled sessions, demote/restore round trips, the spill plane
    row, and the process-wide migration ledger, ISSUE 19)."""
    import concurrent.futures
    import json as _json

    import brpc_tpu.policy  # noqa: F401
    from brpc_tpu import rpc
    from brpc_tpu.rpc import errors as rpc_errors
    lo_seq, _, hi_seq = seq_range.partition("-")
    lo_steps, _, hi_steps = steps_range.partition("-")
    lo_seq, hi_seq = int(lo_seq), int(hi_seq or lo_seq)
    lo_steps, hi_steps = int(lo_steps), int(hi_steps or lo_steps)
    targets = resolve_targets(server)
    channels = []
    for t in targets:
        ch = rpc.Channel()
        ch.init(t, options=rpc.ChannelOptions(timeout_ms=30000,
                                              max_retry=0))
        channels.append(ch)
    try:
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
    except ImportError:
        import os as _os
        sys.path.insert(0, _os.getcwd())
        from examples.example_echo_pb2 import EchoRequest, EchoResponse

    # plain lists, not bvar percentiles: per-session tokens/s can be
    # a small number (long batch decodes) and the latency-percentile
    # buckets would quantize it to 0
    classes = {
        "inter": {"sessions": 0, "ok": 0, "shed": 0, "fail": 0,
                  "tokens": 0, "lat": [], "tps": []},
        "bulk": {"sessions": 0, "ok": 0, "shed": 0, "fail": 0,
                 "tokens": 0, "lat": [], "tps": []},
    }
    lock = threading.Lock()
    mismatches = [0]
    stop_evt = threading.Event()
    prev_sigint = None
    try:
        prev_sigint = signal.signal(signal.SIGINT,
                                    lambda *_: stop_evt.set())
    except ValueError:
        pass

    def one_session(i: int) -> None:
        is_batch = (i % (batch_ratio + 1)) != 0
        tenant = "bulk" if is_batch else "inter"
        # deterministic per-index draws (no RNG: replayable load)
        seq = lo_seq + (i * 13) % max(hi_seq - lo_seq + 1, 1)
        steps = (hi_steps if is_batch
                 else lo_steps + (i * 7) % max(
                     min(hi_steps // 2, hi_steps) - lo_steps + 1, 1))
        tokens = [(i * 31 + j) % 997 for j in range(seq)]
        cntl = rpc.Controller()
        cntl.priority = 3 if is_batch else 0
        cntl.tenant = tenant
        t0 = time.perf_counter_ns()
        resp = channels[i % len(channels)].call_method(
            "Router.Generate", cntl,
            EchoRequest(message=_json.dumps(
                {"tokens": tokens, "steps": steps})), EchoResponse)
        lat_us = (time.perf_counter_ns() - t0) // 1000
        got = None
        if not cntl.failed():
            got = _json.loads(resp.message)["tokens"]
            if verify:
                from examples.disagg_serving.model import \
                    reference_generate
                if got != reference_generate(tokens, steps):
                    with lock:
                        mismatches[0] += 1
        with lock:
            c = classes[tenant]
            c["sessions"] += 1
            if cntl.failed():
                if cntl.error_code_ in (rpc_errors.ELIMIT,
                                        rpc_errors.ELOGOFF):
                    c["shed"] += 1
                else:
                    c["fail"] += 1
            else:
                c["ok"] += 1
                c["tokens"] += len(got)
                c["lat"].append(lat_us)
                if lat_us > 0:
                    c["tps"].append(len(got) * 1e6 / lat_us)

    pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=max_sessions_inflight)
    interval = 1.0 / max(arrival_rps, 0.1)
    t_start = time.monotonic()
    deadline = t_start + duration
    next_fire = t_start
    i = 0
    issued = 0
    while not stop_evt.is_set():
        now = time.monotonic()
        if now >= deadline:
            break
        if now < next_fire:
            time.sleep(min(next_fire - now, 0.01))
            continue
        # OPEN loop: the arrival clock advances whether or not the
        # previous sessions completed
        next_fire += interval
        pool.submit(one_session, i)
        issued += 1
        i += 1
    pool.shutdown(wait=True)
    elapsed = time.monotonic() - t_start
    if prev_sigint is not None:
        try:
            signal.signal(signal.SIGINT, prev_sigint)
        except ValueError:
            pass
    def pct(vals, q):
        if not vals:
            return -1.0
        vals = sorted(vals)
        return round(vals[min(int(len(vals) * q), len(vals) - 1)], 1)

    total_tokens = sum(c["tokens"] for c in classes.values())
    result = {
        "serving": True,
        "targets": targets,
        "arrival_rps": arrival_rps,
        "issued": issued,
        "elapsed_s": round(elapsed, 2),
        "tokens_per_s": round(total_tokens / elapsed, 1) if elapsed
        else 0.0,
        "verify": verify,
        "mismatches": mismatches[0],
        "interrupted": stop_evt.is_set(),
        "per_tenant": {
            name: {
                "sessions": c["sessions"], "ok": c["ok"],
                "shed": c["shed"], "failures": c["fail"],
                "tokens": c["tokens"],
                "latency_p50_us": pct(c["lat"], 0.5),
                "latency_p99_us": pct(c["lat"], 0.99),
                "session_tokens_per_s_p50": pct(c["tps"], 0.5),
                "session_tokens_per_s_p99": pct(c["tps"], 0.99),
            } for name, c in classes.items()},
    }
    stats = collect_serving_stats()
    if stats:
        result["serving_status"] = stats
        # kv-load route counts (ISSUE 15): which path carried the
        # sessions' KV bytes into the pool — adopted (host claims in
        # place) / scattered (device segs / parked native handles) /
        # materialized (the PR-14 fallback) — plus the host-copy-passes
        # byte counter.  Gated like serving_status: the counters are
        # process-global, so a remote-only press run would otherwise
        # report its own all-zero locals as the server's route truth.
        try:
            from brpc_tpu.serving import kv_load_stats
            result["kv_load_routes"] = kv_load_stats()
        except Exception:
            pass
        # prefix-sharing truth (ISSUE 16): each in-process pool's CoW
        # block — shared_blocks / prefix_hits / cow_splits / the
        # physical-vs-logical sharing_ratio / fill-route counters —
        # lifted out of the per-service describe_serving() blocks so a
        # press run can assert capacity claims without scraping
        # /status.  Same in-process gate as serving_status: remote-only
        # runs omit it instead of reporting local zeros.
        prefix = {
            label: blk["pool"]["prefix"]
            for label, blk in stats.items()
            if isinstance(blk.get("pool"), dict)
            and "prefix" in blk["pool"]}
        if prefix:
            result["kv_prefix"] = prefix
        # tiered-memory truth (ISSUE 19): each in-process pool's
        # host-tier block — resident vs spilled sessions, demote /
        # restore round trips with restore_p50_us, the spill
        # plane-health row, and the process-wide migration ledger
        # (migrations in/out, cutovers, aborts, bytes_moved).  Same
        # in-process gate: remote-only runs omit it.
        tiers = {
            label: blk["pool"]["tiers"]
            for label, blk in stats.items()
            if isinstance(blk.get("pool"), dict)
            and "tiers" in blk["pool"]}
        if tiers:
            result["kv_tiers"] = tiers
    print(json.dumps(result), file=out)
    for ch in channels:
        ch.close()
    return result


def apply_shm_stripes(n: int) -> None:
    """``--shm-stripes N``: force the striped shm plane (ISSUE 12) —
    N SPSC ring pairs per segment, round-robin for unary frames,
    stream-id affinity for streams.  0 keeps auto (1 on 1-core hosts).
    Whether stripes actually carried bytes is visible in the summary's
    ``rpc_fabric_route_shm_stripe_*`` counters — asserted, not
    assumed."""
    if n <= 0:
        return
    import brpc_tpu.ici.fabric  # noqa: F401 — defines ici_shm_stripes
    from brpc_tpu.butil import flags as _fl
    _fl.set_flag("ici_shm_stripes", n)


def run_press(server: str, method: str, request_json: str,
              qps: int = 0, duration: float = 5.0, concurrency: int = 8,
              proto: Optional[str] = None, protocol: str = "tpu_std",
              priority: Optional[str] = None, tenant: Optional[str] = None,
              max_retry: Optional[int] = None,
              bulk_plane: str = "auto", shm_stripes: int = 0,
              usercode_pool: str = "auto",
              out=sys.stderr) -> dict:
    import brpc_tpu.policy  # noqa: F401 — registers protocols
    from brpc_tpu import rpc, bvar
    from brpc_tpu.codec import json2pb
    from brpc_tpu.rpc import errors as rpc_errors
    apply_bulk_plane(bulk_plane)
    apply_shm_stripes(shm_stripes)
    apply_usercode_pool(usercode_pool)

    if proto:
        req_cls, resp_cls = _load_classes(proto)
        request = json2pb.dict_to_pb(json.loads(request_json or "{}"), req_cls)
    else:
        req_cls = resp_cls = None
        request = (request_json or "").encode()

    pri_wheel = parse_weighted_mix(priority, int_keys=True) \
        if priority else []
    tenant_wheel = parse_weighted_mix(tenant) if tenant else []
    # a stride coprime with the tenant wheel decorrelates it from the
    # priority wheel (equal lengths would pin each band to one tenant)
    ten_stride = 1
    if tenant_wheel:
        ten_stride = next(s for s in (7, 11, 13, 17, 19, 23, 1)
                          if len(tenant_wheel) % s != 0 or s == 1)
    targets = resolve_targets(server)
    channels = []
    for t in targets:
        copts = rpc.ChannelOptions(protocol=protocol, timeout_ms=10000)
        if max_retry is not None:
            copts.max_retry = max_retry
        ch = rpc.Channel()
        ch.init(t, options=copts)
        channels.append(ch)
    recorder = bvar.LatencyRecorder()
    errors_count = [0]
    sent = [0]
    per_ep = {t: {"sent": 0, "errors": 0} for t in targets}
    # per (priority, tenant) class: sent / shed (ELIMIT) / errors /
    # latency recorder — the overload bench's fairness view
    per_class: dict = {}
    lock = threading.Lock()
    deadline = time.monotonic() + duration
    interval = concurrency / qps if qps > 0 else 0.0
    # graceful SIGINT (reference tools/rpc_press): ^C stops ISSUING, the
    # in-flight calls run to completion, and the final latency/QPS
    # summary still prints — instead of a KeyboardInterrupt mid-run that
    # loses the whole measurement.  Installable only from the main
    # thread; elsewhere the default (hard) behavior is kept.
    stop_evt = threading.Event()
    prev_sigint = None
    try:
        prev_sigint = signal.signal(signal.SIGINT,
                                    lambda *_: stop_evt.set())
    except ValueError:
        pass

    def worker(wid: int):
        next_fire = time.monotonic()
        i = 0
        while not stop_evt.is_set() and time.monotonic() < deadline:
            if interval:
                now = time.monotonic()
                if now < next_fire:
                    time.sleep(min(next_fire - now, 0.05))
                    continue
                next_fire += interval
            # workers spread across the endpoint list round-robin, each
            # starting at its own offset so N workers cover N endpoints
            # even with concurrency == len(targets)
            idx = (wid + i) % len(targets)
            cntl = rpc.Controller()
            pri = pri_wheel[(wid + i) % len(pri_wheel)] if pri_wheel \
                else None
            ten = tenant_wheel[(wid + ten_stride * i) % len(tenant_wheel)] \
                if tenant_wheel else ""
            if pri is not None:
                cntl.priority = pri
            if ten:
                cntl.tenant = ten
            i += 1
            t0 = time.perf_counter_ns()
            channels[idx].call_method(method, cntl, request, resp_cls)
            lat_us = (time.perf_counter_ns() - t0) // 1000
            shed = (cntl.error_code_ == rpc_errors.ELIMIT
                    and cntl.retry_after_ms > 0)
            with lock:
                sent[0] += 1
                per_ep[targets[idx]]["sent"] += 1
                if pri_wheel or tenant_wheel:
                    ckey = f"p{pri if pri is not None else '-'}" + \
                        (f"/{ten}" if ten else "")
                    cls = per_class.get(ckey)
                    if cls is None:
                        cls = per_class[ckey] = {
                            "sent": 0, "shed": 0, "errors": 0,
                            "rec": bvar.LatencyRecorder()}
                    cls["sent"] += 1
                    if shed:
                        cls["shed"] += 1
                    elif cntl.failed():
                        cls["errors"] += 1
                    else:
                        cls["rec"] << lat_us
                if cntl.failed():
                    errors_count[0] += 1
                    per_ep[targets[idx]]["errors"] += 1
                else:
                    recorder << lat_us
    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(concurrency)]
    t_start = time.monotonic()
    for t in threads: t.start()
    for t in threads: t.join()      # interrupted workers drain in-flight
    elapsed = time.monotonic() - t_start
    if prev_sigint is not None:
        try:
            signal.signal(signal.SIGINT, prev_sigint)
        except ValueError:
            pass
    from brpc_tpu.bvar import SamplerCollector
    SamplerCollector.instance().sample_once()
    result = {
        "sent": sent[0],
        "errors": errors_count[0],
        "qps": round(sent[0] / elapsed, 1),
        "avg_latency_us": round(recorder.latency(), 1),
        "max_latency_us": recorder.max_latency(),
        "p99_latency_us": recorder.latency_percentile(0.99),
        "elapsed_s": round(elapsed, 2),
        "interrupted": stop_evt.is_set(),
        "bulk_plane": bulk_plane,
        "shm_stripes": shm_stripes,
        "usercode_pool": usercode_pool,
    }
    # isolation capability + per-in-process-server pool stats (ROADMAP
    # 4c): a SKIPping host records WHY it cannot scale
    try:
        result["usercode_pool_stats"] = collect_usercode_pool_stats()
    except Exception:
        pass
    # which byte mover actually carried the run's payloads (ici/route.py
    # counters; empty off the fabric) — the "chosen route" in the summary
    try:
        from brpc_tpu.ici.route import route_stats
        rs = route_stats()
        if rs:
            result["routes"] = rs
    except Exception:
        pass
    if len(targets) > 1:
        result["per_endpoint"] = {
            t: {**c, "qps": round(c["sent"] / elapsed, 1)}
            for t, c in per_ep.items()}
    if per_class:
        result["per_class"] = {
            k: {"sent": c["sent"], "shed": c["shed"],
                "errors": c["errors"],
                "avg_latency_us": round(c["rec"].latency(), 1),
                "p99_latency_us": c["rec"].latency_percentile(0.99)}
            for k, c in sorted(per_class.items())}
    print(json.dumps(result), file=out)
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--server", required=True,
                    help="endpoint, comma-separated endpoint list, or "
                         "naming url (mesh://, pod://name, list://…)")
    ap.add_argument("--method", default=None,
                    help="full method name (required except with "
                         "--serving, which drives Router.Generate)")
    ap.add_argument("--request", default="{}")
    ap.add_argument("--qps", type=int, default=0, help="0 = unthrottled")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--proto", default=None,
                    help="module:RequestCls,ResponseCls")
    ap.add_argument("--protocol", default="tpu_std")
    ap.add_argument("--priority", default=None,
                    help="priority band (0=critical..3=sheddable) or a "
                         "band:weight mix, e.g. '0:1,3:3'")
    ap.add_argument("--tenant", default=None,
                    help="tenant name or tenant:weight mix, e.g. 'a:2,b:1'")
    ap.add_argument("--max-retry", type=int, default=None,
                    help="per-call retry budget (shed retries honor the "
                         "server's retry_after_ms hint)")
    ap.add_argument("--bulk-plane", default="auto", choices=BULK_PLANES,
                    help="pin the fabric bulk tier for this run: auto "
                         "(route table: shm > uds/tcp > inline), shm, "
                         "uds (shm off), inline (both descriptor planes "
                         "off); the summary reports per-route counters")
    ap.add_argument("--usercode-pool", default="auto",
                    choices=USERCODE_POOLS,
                    help="pin the usercode-pool backend for servers "
                         "hosted in this process (auto keeps each "
                         "server's resolution; off records the pin); "
                         "the summary reports the probed isolation "
                         "capability and per-server pool stats")
    ap.add_argument("--shm-stripes", type=int, default=0,
                    help="force N shm ring stripes per segment (0 = "
                         "auto: 1 on 1-core hosts, else min(4, cores)); "
                         "per-stripe counters appear in the summary's "
                         "routes")
    ap.add_argument("--fanout", type=int, default=0,
                    help="drive ONE ParallelChannel over the first N "
                         "resolved members (compiled collective route "
                         "where registered, per-member RPCs otherwise); "
                         "summary adds fan-out p50/p99 and per-route "
                         "call counts")
    ap.add_argument("--fanout-shard-bytes", type=int, default=512,
                    help="bytes per member shard in --fanout mode")
    ap.add_argument("--serving", action="store_true",
                    help="open-loop serving session generator against "
                         "a Router.Generate front door: mixed "
                         "interactive/batch tenants at a fixed arrival "
                         "rate; summary reports per-tenant tokens/s "
                         "p50/p99 and pool occupancy")
    ap.add_argument("--serving-arrival-rps", type=float, default=20.0,
                    help="session arrivals per second (open loop: the "
                         "clock never waits for completions)")
    ap.add_argument("--serving-batch-ratio", type=int, default=3,
                    help="batch sessions per interactive session")
    ap.add_argument("--serving-seq", default="32-96",
                    help="prompt length range, e.g. 32-96")
    ap.add_argument("--serving-steps", default="8-64",
                    help="decode steps range: interactive draws from "
                         "the low half, batch takes the high bound")
    ap.add_argument("--serving-verify", action="store_true",
                    help="verify every completion against the "
                         "single-process reference (slow: reference "
                         "prefill per session)")
    args = ap.parse_args(argv)
    if args.serving:
        run_press_serving(args.server, duration=args.duration,
                          arrival_rps=args.serving_arrival_rps,
                          batch_ratio=args.serving_batch_ratio,
                          seq_range=args.serving_seq,
                          steps_range=args.serving_steps,
                          max_sessions_inflight=max(args.concurrency, 8),
                          verify=args.serving_verify, out=sys.stdout)
        return 0
    if not args.method:
        raise SystemExit("rpc_press: --method is required "
                         "(except with --serving)")
    if args.fanout > 0:
        run_press_fanout(args.server, args.method, args.fanout,
                         duration=args.duration,
                         concurrency=args.concurrency,
                         shard_bytes=args.fanout_shard_bytes,
                         out=sys.stdout)
        return 0
    run_press(args.server, args.method, args.request, args.qps,
              args.duration, args.concurrency, args.proto, args.protocol,
              priority=args.priority, tenant=args.tenant,
              max_retry=args.max_retry, bulk_plane=args.bulk_plane,
              shm_stripes=args.shm_stripes,
              usercode_pool=args.usercode_pool, out=sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
