"""parallel_http: mass concurrent HTTP fetcher.

Reference: tools/parallel_http — fetch many URLs concurrently, report
success/latency.  Used operationally to probe fleets of admin endpoints.

    python -m brpc_tpu.tools.parallel_http --urls urls.txt --concurrency 32
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.request
from typing import List


def fetch_all(urls: List[str], concurrency: int = 16,
              timeout: float = 5.0, out=sys.stderr) -> dict:
    results = []
    lock = threading.Lock()
    queue = list(enumerate(urls))

    def worker():
        while True:
            with lock:
                if not queue:
                    return
                idx, url = queue.pop()
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(url, timeout=timeout) as r:
                    body = r.read()
                    rec = (idx, url, r.status, len(body),
                           time.perf_counter() - t0, "")
            except Exception as e:
                rec = (idx, url, 0, 0, time.perf_counter() - t0, str(e))
            with lock:
                results.append(rec)

    threads = [threading.Thread(target=worker)
               for _ in range(min(concurrency, max(len(urls), 1)))]
    t0 = time.monotonic()
    for t in threads: t.start()
    for t in threads: t.join()
    elapsed = time.monotonic() - t0
    ok = sum(1 for r in results if 200 <= r[2] < 300)
    summary = {
        "urls": len(urls), "ok": ok, "failed": len(urls) - ok,
        "elapsed_s": round(elapsed, 2),
        "avg_latency_ms": round(
            sum(r[4] for r in results) / max(len(results), 1) * 1000, 1),
    }
    print(json.dumps(summary), file=out)
    return {"summary": summary, "results": sorted(results)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--urls", required=True,
                    help="file with one URL per line, or comma-joined list")
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    if "," in args.urls or args.urls.startswith("http"):
        urls = [u for u in args.urls.split(",") if u]
    else:
        with open(args.urls) as f:
            urls = [line.strip() for line in f if line.strip()]
    fetch_all(urls, args.concurrency, args.timeout, out=sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
