"""rpc_replay: replay rpc_dump capture files against a server.

Reference: tools/rpc_replay — reads sampled frames recorded by rpc_dump
(see brpc_tpu/rpc/rpc_dump.py) and re-sends them, reporting success rate
and latency.  Dumped frames are raw tpu_std bytes; replay re-correlates
each with a fresh id so responses resolve normally.

    python -m brpc_tpu.tools.rpc_replay --server mem://echo --dir ./rpc_dump \
        [--times 2] [--qps 0]
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def run_replay(server: str, dump_dir: str, times: int = 1, qps: int = 0,
               timeout_s: float = 10.0, out=sys.stderr) -> dict:
    import brpc_tpu.policy  # noqa: F401
    from brpc_tpu.butil.endpoint import parse_endpoint
    from brpc_tpu.butil.iobuf import IOBuf
    from brpc_tpu.proto import rpc_meta_pb2 as meta_pb
    from brpc_tpu.rpc import rpc_dump
    from brpc_tpu.rpc.controller import Controller
    from brpc_tpu.rpc.socket_map import SocketMap
    from brpc_tpu.rpc.input_messenger import InputMessenger
    from brpc_tpu.policy import tpu_std
    from brpc_tpu.bthread import id as bthread_id

    files = rpc_dump.list_dump_files(dump_dir)
    if not files:
        print(json.dumps({"error": f"no dump files in {dump_dir}"}), file=out)
        return {"sent": 0, "ok": 0}

    ep = parse_endpoint(server)
    messenger = InputMessenger(server=None)
    sock = SocketMap.instance().get_socket(ep, messenger)
    interval = 1.0 / qps if qps > 0 else 0.0
    inflight = []
    sent = 0
    t0 = time.monotonic()

    for _ in range(times):
        for path in files:
            for frame in rpc_dump.load_dumped_frames(path):
                meta_size = int.from_bytes(frame[4:8], "big")
                meta = meta_pb.RpcMeta()
                meta.ParseFromString(frame[12:12 + meta_size])
                body = frame[12 + meta_size:]
                cntl = Controller()
                cntl.timeout_ms = int(timeout_s * 1000)
                cntl.max_retry = 0
                cntl._cid = bthread_id.create_ranged(
                    cntl, cntl._on_rpc_event, 1)
                cid = bthread_id.with_version(cntl._cid, 0)
                cntl._start_us = time.monotonic_ns() // 1000
                meta.correlation_id = cid
                new_meta = meta.SerializeToString()
                buf = IOBuf()
                buf.append(tpu_std.MAGIC)
                buf.append(len(new_meta).to_bytes(4, "big"))
                buf.append(len(body).to_bytes(4, "big"))
                buf.append(new_meta)
                buf.append(body)
                sock.write(buf, notify_cid=cid)
                inflight.append(cntl)
                sent += 1
                if interval:
                    time.sleep(interval)

    ok = 0
    errors_n = 0
    deadline = time.monotonic() + timeout_s
    for cntl in inflight:
        remaining = max(deadline - time.monotonic(), 0.01)
        try:
            cntl.join(remaining)
            if cntl.failed():
                errors_n += 1
            else:
                ok += 1
        except TimeoutError:
            errors_n += 1
    elapsed = time.monotonic() - t0
    result = {"sent": sent, "ok": ok, "errors": errors_n,
              "elapsed_s": round(elapsed, 2), "files": len(files),
              "qps": round(sent / elapsed, 1) if elapsed else 0}
    print(json.dumps(result), file=out)
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--server", required=True)
    ap.add_argument("--dir", default="./rpc_dump")
    ap.add_argument("--times", type=int, default=1)
    ap.add_argument("--qps", type=int, default=0)
    args = ap.parse_args(argv)
    run_replay(args.server, args.dir, args.times, args.qps, out=sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
